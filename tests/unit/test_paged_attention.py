"""Paged attention kernel tests (parity role: reference
``tests/unit/inference/v2/kernels/ragged_ops`` — kernel vs reference
comparisons). Pools use the combined page layout [NB, 2, Hkv, bs, D]
(K = index 0, V = index 1; see ops/pallas/paged_attention.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.pallas.paged_attention import (
    paged_chunk_attention, paged_chunk_attention_reference,
    paged_decode_attention, paged_decode_attention_reference,
    paged_decode_attention_step, paged_decode_attention_step_reference)


def _setup(rng, S, H, D, Hkv, NB, bs, MB):
    q = jnp.asarray(rng.randn(S, H, D), jnp.float32)
    kv = jnp.asarray(rng.randn(NB, 2, Hkv, bs, D), jnp.float32)
    bt = jnp.asarray(rng.permutation(NB)[:S * MB].reshape(S, MB), jnp.int32)
    return q, kv, bt


class TestPagedDecode:

    @pytest.mark.parametrize("Hkv", [2, 8])
    def test_matches_reference(self, Hkv):
        rng = np.random.RandomState(0)
        S, H, D, NB, bs, MB = 5, 8, 64, 32, 8, 4
        q, kv, bt = _setup(rng, S, H, D, Hkv, NB, bs, MB)
        cl = jnp.asarray([1, 8, 13, 30, 32], jnp.int32)
        out = paged_decode_attention(q, kv, bt, cl)
        ref = paged_decode_attention_reference(q, kv, bt, cl)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_empty_rows_zero(self):
        rng = np.random.RandomState(1)
        q, kv, bt = _setup(rng, 3, 4, 64, 2, 16, 8, 2)
        cl = jnp.asarray([5, 0, 0], jnp.int32)
        out = np.asarray(paged_decode_attention(q, kv, bt, cl))
        assert np.all(out[1:] == 0)
        assert np.any(out[0] != 0)

    def test_jit(self):
        rng = np.random.RandomState(2)
        q, kv, bt = _setup(rng, 4, 8, 64, 4, 16, 8, 2)
        cl = jnp.asarray([3, 9, 16, 1], jnp.int32)
        out = jax.jit(paged_decode_attention)(q, kv, bt, cl)
        ref = paged_decode_attention_reference(q, kv, bt, cl)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_large_d_manual_dma_path(self):
        """D = 128 exercises the manual-DMA two-slot pipeline (the serving
        path) rather than the BlockSpec fallback."""
        rng = np.random.RandomState(6)
        S, H, Hkv, D, NB, bs, MB = 3, 4, 2, 128, 16, 8, 4
        q, kv, bt = _setup(rng, S, H, D, Hkv, NB, bs, MB)
        cl = jnp.asarray([2, 17, 32], jnp.int32)
        out = paged_decode_attention(q, kv, bt, cl)
        ref = paged_decode_attention_reference(q, kv, bt, cl)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-4)


class TestPagedChunkBatched:

    def test_matches_per_slot_reference(self):
        from deepspeed_tpu.ops.pallas.paged_attention import (
            paged_chunk_attention_batched, paged_chunk_attention_batched_reference)
        rng = np.random.RandomState(11)
        NC, Cs, H, Hkv, D, bs, MB = 4, 16, 8, 2, 64, 8, 6
        NB = NC * MB + 2
        kv = jnp.asarray(rng.randn(NB, 2, Hkv, bs, D), jnp.float32)
        q = jnp.asarray(rng.randn(NC, Cs, H, D), jnp.float32)
        bt = jnp.asarray(rng.permutation(NB - 1)[:NC * MB].reshape(NC, MB) + 1,
                         jnp.int32)
        q0s = jnp.asarray([0, 13, 40, 0], jnp.int32)
        ctxs = jnp.asarray([16, 29, 56, 0], jnp.int32)   # last slot empty
        out = jax.jit(paged_chunk_attention_batched)(q, kv, bt, q0s, ctxs)
        ref = paged_chunk_attention_batched_reference(q, kv, bt, q0s, ctxs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-4)
        assert np.all(np.asarray(out)[3] == 0)


class TestPagedDecodeStep:
    """Fused decode step: prior-context flash + inline current token + page
    write, pool aliased through. Edge cases: ctx 1 (no pages yet), page
    boundary, ctx 0 (padding row: no write, zero output)."""

    @pytest.mark.parametrize("Hkv,ctxs", [
        (8, [9, 17, 30]),
        (2, [1, 8, 32]),          # GQA; ctx=1; exact page boundary
        (4, [0, 5]),              # padding row
    ])
    def test_matches_reference(self, Hkv, ctxs):
        rng = np.random.RandomState(7)
        S, H, D, bs = len(ctxs), 8, 64, 8
        MB = 4
        NB = S * MB + 2
        kv = jnp.asarray(rng.randn(NB, 2, Hkv, bs, D), jnp.float32)
        q = jnp.asarray(rng.randn(S, H, D), jnp.float32)
        kn = jnp.asarray(rng.randn(S, Hkv, D), jnp.float32)
        vn = jnp.asarray(rng.randn(S, Hkv, D), jnp.float32)
        # disjoint per-sequence page tables (pages are exclusive in serving)
        bt = jnp.asarray(rng.permutation(NB - 1)[:S * MB].reshape(S, MB) + 1,
                         jnp.int32)
        cl = jnp.asarray(ctxs, jnp.int32)
        out, kvf = jax.jit(paged_decode_attention_step)(q, kn, vn, kv, bt, cl)
        orf, kvrf = paged_decode_attention_step_reference(q, kn, vn, kv,
                                                          bt, cl)
        np.testing.assert_allclose(np.asarray(out), np.asarray(orf),
                                   atol=2e-5, rtol=2e-4)
        np.testing.assert_array_equal(np.asarray(kvf), np.asarray(kvrf))
        for i, c in enumerate(ctxs):
            if c == 0:
                assert np.all(np.asarray(out)[i] == 0)

    def test_manual_dma_path_d128(self):
        rng = np.random.RandomState(8)
        S, H, Hkv, D, bs, MB = 2, 4, 2, 128, 8, 3
        NB = S * MB + 1
        kv = jnp.asarray(rng.randn(NB, 2, Hkv, bs, D), jnp.float32)
        q = jnp.asarray(rng.randn(S, H, D), jnp.float32)
        kn = jnp.asarray(rng.randn(S, Hkv, D), jnp.float32)
        vn = jnp.asarray(rng.randn(S, Hkv, D), jnp.float32)
        bt = jnp.asarray(rng.permutation(NB - 1)[:S * MB].reshape(S, MB) + 1,
                         jnp.int32)
        cl = jnp.asarray([6, 20], jnp.int32)
        out, kvf = paged_decode_attention_step(q, kn, vn, kv, bt, cl)
        orf, kvrf = paged_decode_attention_step_reference(q, kn, vn, kv,
                                                          bt, cl)
        np.testing.assert_allclose(np.asarray(out), np.asarray(orf),
                                   atol=2e-5, rtol=2e-4)
        np.testing.assert_array_equal(np.asarray(kvf), np.asarray(kvrf))


class TestPagedChunk:

    @pytest.mark.parametrize("q_start,ctx", [(0, 16), (13, 29), (40, 56)])
    def test_matches_reference(self, q_start, ctx):
        rng = np.random.RandomState(3)
        C, H, D, Hkv, NB, bs, MB = 16, 8, 64, 2, 32, 8, 8
        q = jnp.asarray(rng.randn(C, H, D), jnp.float32)
        kv = jnp.asarray(rng.randn(NB, 2, Hkv, bs, D), jnp.float32)
        bt = jnp.asarray(rng.permutation(NB)[:MB], jnp.int32)
        out = paged_chunk_attention(q, kv, bt, q_start, ctx)
        ref = paged_chunk_attention_reference(q, kv, bt, q_start, ctx)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_empty_ctx_zero(self):
        rng = np.random.RandomState(4)
        q = jnp.asarray(rng.randn(8, 4, 64), jnp.float32)
        kv = jnp.asarray(rng.randn(16, 2, 2, 8, 64), jnp.float32)
        bt = jnp.zeros((4,), jnp.int32)
        out = np.asarray(paged_chunk_attention(q, kv, bt, 0, 0))
        assert np.all(out == 0)

    def test_matches_dense_flash_prefill(self):
        """Chunk attention over pages == dense causal attention on the same KV."""
        from deepspeed_tpu.ops.attention import reference_attention
        rng = np.random.RandomState(5)
        C, H, D, NB, bs = 16, 4, 64, 8, 8
        MB = C // bs
        q = jnp.asarray(rng.randn(C, H, D), jnp.float32)
        kd = jnp.asarray(rng.randn(C, H, D), jnp.float32)
        vd = jnp.asarray(rng.randn(C, H, D), jnp.float32)
        bt = jnp.asarray([3, 5], jnp.int32)
        kv_pages = jnp.zeros((NB, 2, H, bs, D), jnp.float32)
        kv_pages = kv_pages.at[bt, 0].set(
            jnp.moveaxis(kd.reshape(MB, bs, H, D), 1, 2))
        kv_pages = kv_pages.at[bt, 1].set(
            jnp.moveaxis(vd.reshape(MB, bs, H, D), 1, 2))
        out = paged_chunk_attention(q, kv_pages, bt, 0, C)
        ref = reference_attention(q[None], kd[None], vd[None], causal=True)[0]
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)


class TestPackedFlash:
    """flash_attention_packed: the prefill-from-zero fast path's kernel
    (segment-masked packed flash; ragged_model.build_prefill_forward)."""

    @pytest.mark.parametrize("Hkv", [4, 2])
    def test_matches_per_segment_reference(self, Hkv):
        from deepspeed_tpu.ops.attention import reference_attention
        from deepspeed_tpu.ops.pallas.flash_attention import (
            flash_attention_packed)
        rng = np.random.RandomState(3)
        H, D = 4, 32
        lens = [7, 19, 3, 33]
        R = sum(lens)
        seg = np.concatenate([np.full(n, i, np.int32)
                              for i, n in enumerate(lens)])
        q = jnp.asarray(rng.randn(R, H, D), jnp.float32)
        k = jnp.asarray(rng.randn(R, Hkv, D), jnp.float32)
        v = jnp.asarray(rng.randn(R, Hkv, D), jnp.float32)
        out, lse = flash_attention_packed(q, k, v, jnp.asarray(seg),
                                          with_lse=True)
        rep = H // Hkv
        r0 = 0
        for n in lens:
            sl = slice(r0, r0 + n)
            ref = reference_attention(
                q[None, sl], jnp.repeat(k[None, sl], rep, 2),
                jnp.repeat(v[None, sl], rep, 2), causal=True)[0]
            np.testing.assert_allclose(np.asarray(out[sl]), np.asarray(ref),
                                       atol=2e-5)
            r0 += n
        assert bool(jnp.isfinite(lse).all())

    def test_padding_rows_are_isolated(self):
        """Rows with segment -1 (slot padding) must not leak into real rows."""
        from deepspeed_tpu.ops.attention import reference_attention
        from deepspeed_tpu.ops.pallas.flash_attention import (
            flash_attention_packed)
        rng = np.random.RandomState(4)
        H, D = 2, 16
        # real rows 0..9 (segment 0), pad rows 10..15 (segment -1) with huge
        # values that would visibly corrupt the output if attended
        seg = np.asarray([0] * 10 + [-1] * 6, np.int32)
        q = jnp.asarray(rng.randn(16, H, D), jnp.float32)
        k = jnp.asarray(rng.randn(16, H, D), jnp.float32).at[10:].set(100.0)
        v = jnp.asarray(rng.randn(16, H, D), jnp.float32).at[10:].set(1e6)
        out = flash_attention_packed(q, k, v, jnp.asarray(seg))
        ref = reference_attention(q[None, :10], k[None, :10], v[None, :10],
                                  causal=True)[0]
        np.testing.assert_allclose(np.asarray(out[:10]), np.asarray(ref),
                                   atol=2e-5)
        assert bool(jnp.isfinite(out).all())

    def test_jit_and_nondivisible_rows(self):
        from deepspeed_tpu.ops.pallas.flash_attention import (
            flash_attention_packed)
        rng = np.random.RandomState(5)
        R, H, D = 200, 2, 32   # R > 128 and not a multiple of 128 -> pads
        seg = np.repeat([0, 1], 100).astype(np.int32)
        q = jnp.asarray(rng.randn(R, H, D), jnp.float32)
        k = jnp.asarray(rng.randn(R, H, D), jnp.float32)
        o1 = flash_attention_packed(q, k, k, jnp.asarray(seg))
        o2 = jax.jit(flash_attention_packed)(q, k, k, jnp.asarray(seg))
        assert o1.shape == (R, H, D)
        np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), atol=1e-6)


class TestPagedDecodeSidebuf:
    """Fused frozen-prefix + side-slab decode kernel (the side-buffer
    multistep schedule's attention body). Reference = the round-4 two-piece
    computation: paged prefix with lse, dense side piece, lse merge."""

    @pytest.mark.parametrize("Hkv,j", [(2, 0), (2, 3), (4, 5), (8, 7)])
    def test_matches_reference(self, Hkv, j):
        from deepspeed_tpu.ops.pallas.paged_attention import (
            paged_decode_attention_sidebuf,
            paged_decode_attention_sidebuf_reference)
        rng = np.random.RandomState(3)
        S, H, D, bs, MB, C = 4, 8, 128, 8, 3, 8
        NB = S * MB + 1
        q = jnp.asarray(rng.randn(S, H, D), jnp.float32)
        kv = jnp.asarray(rng.randn(NB, 2, Hkv, bs, D), jnp.float32)
        bt = jnp.asarray(rng.permutation(NB - 1)[:S * MB].reshape(S, MB) + 1,
                         jnp.int32)
        # prefix 0 (fresh sequence: all context in the side slab), mid-page,
        # page boundary, full
        prefix = jnp.asarray([0, 5, bs, MB * bs], jnp.int32)
        sk = jnp.asarray(rng.randn(S, C, Hkv, D), jnp.float32)
        sv = jnp.asarray(rng.randn(S, C, Hkv, D), jnp.float32)
        out = jax.jit(paged_decode_attention_sidebuf,
                      static_argnames=())(q, kv, bt, prefix, sk, sv, j)
        ref = paged_decode_attention_sidebuf_reference(q, kv, bt, prefix,
                                                       sk, sv, j)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-4)

    @pytest.mark.parametrize("window,j", [(12, 0), (12, 6), (4, 7)])
    def test_windowed_matches_reference(self, window, j):
        """Sliding window over position prefix + j: the page-side window
        start moves with j; side columns below j+1-window hide."""
        from deepspeed_tpu.ops.pallas.paged_attention import (
            paged_decode_attention_sidebuf,
            paged_decode_attention_sidebuf_reference)
        rng = np.random.RandomState(9)
        S, H, Hkv, D, bs, MB, C = 3, 4, 2, 128, 8, 3, 8
        NB = S * MB + 1
        q = jnp.asarray(rng.randn(S, H, D), jnp.float32)
        kv = jnp.asarray(rng.randn(NB, 2, Hkv, bs, D), jnp.float32)
        bt = jnp.asarray(rng.permutation(NB - 1)[:S * MB].reshape(S, MB) + 1,
                         jnp.int32)
        prefix = jnp.asarray([0, 7, 2 * bs + 3], jnp.int32)
        sk = jnp.asarray(rng.randn(S, C, Hkv, D), jnp.float32)
        sv = jnp.asarray(rng.randn(S, C, Hkv, D), jnp.float32)
        out = paged_decode_attention_sidebuf(q, kv, bt, prefix, sk, sv, j,
                                             window=window)
        ref = paged_decode_attention_sidebuf_reference(
            q, kv, bt, prefix, sk, sv, j, window=window)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-4)


class TestInt8Pages:
    """int8 KV pages: kernels with (int8 values, per-token-head scales) must
    match the bf16/f32 reference run on the dequantized pages exactly (the
    dequant is algebraically folded, not approximated — scale commutes
    through the dots)."""

    def _qpages(self, rng, NB, Hkv, bs, D):
        from deepspeed_tpu.ops.pallas.paged_attention import kv_quantize_rows
        kv = jnp.asarray(rng.randn(NB, 2, Hkv, bs, D), jnp.float32)
        kvq, sc = kv_quantize_rows(kv)
        kvd = kvq.astype(jnp.float32) * sc[..., None]
        return kvq, sc, kvd

    def test_decode_matches_dequant_reference(self):
        from deepspeed_tpu.ops.pallas.paged_attention import (
            paged_decode_attention, paged_decode_attention_reference)
        rng = np.random.RandomState(21)
        S, H, Hkv, D, bs, MB = 3, 8, 2, 128, 128, 2
        NB = S * MB + 1
        kvq, sc, kvd = self._qpages(rng, NB, Hkv, bs, D)
        q = jnp.asarray(rng.randn(S, H, D), jnp.float32)
        bt = jnp.asarray(rng.permutation(NB - 1)[:S * MB].reshape(S, MB) + 1,
                         jnp.int32)
        cl = jnp.asarray([5, 130, 256], jnp.int32)
        out = paged_decode_attention(q, kvq, bt, cl, kv_scales=sc)
        ref = paged_decode_attention_reference(q, kvd, bt, cl)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=3e-5, rtol=3e-4)

    def test_sidebuf_matches_dequant_reference(self):
        from deepspeed_tpu.ops.pallas.paged_attention import (
            paged_decode_attention_sidebuf,
            paged_decode_attention_sidebuf_reference)
        rng = np.random.RandomState(22)
        S, H, Hkv, D, bs, MB, C = 3, 4, 2, 128, 128, 2, 8
        NB = S * MB + 1
        kvq, sc, kvd = self._qpages(rng, NB, Hkv, bs, D)
        q = jnp.asarray(rng.randn(S, H, D), jnp.float32)
        bt = jnp.asarray(rng.permutation(NB - 1)[:S * MB].reshape(S, MB) + 1,
                         jnp.int32)
        prefix = jnp.asarray([0, 70, 200], jnp.int32)
        sk = jnp.asarray(rng.randn(S, C, Hkv, D), jnp.float32)
        sv = jnp.asarray(rng.randn(S, C, Hkv, D), jnp.float32)
        out = paged_decode_attention_sidebuf(q, kvq, bt, prefix, sk, sv, 5,
                                             kv_scales=sc)
        ref = paged_decode_attention_sidebuf_reference(q, kvd, bt, prefix,
                                                       sk, sv, 5)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=3e-5, rtol=3e-4)

    def test_step_quantizes_new_rows(self):
        from deepspeed_tpu.ops.pallas.paged_attention import (
            kv_quantize_rows, paged_decode_attention_step,
            paged_decode_attention_step_reference)
        rng = np.random.RandomState(23)
        S, H, Hkv, D, bs, MB = 2, 4, 2, 128, 128, 2
        NB = S * MB + 1
        kvq, sc, kvd = self._qpages(rng, NB, Hkv, bs, D)
        q = jnp.asarray(rng.randn(S, H, D), jnp.float32)
        kn = jnp.asarray(rng.randn(S, Hkv, D), jnp.float32)
        vn = jnp.asarray(rng.randn(S, Hkv, D), jnp.float32)
        bt = jnp.asarray(rng.permutation(NB - 1)[:S * MB].reshape(S, MB) + 1,
                         jnp.int32)
        cl = jnp.asarray([6, 140], jnp.int32)
        out, kvf, scf = paged_decode_attention_step(
            q, kn, vn, kvq, bt, cl, kv_scales=sc)
        # the kernel attends the CURRENT token at full precision from
        # registers (quantization happens at the page write, for future
        # reads) — so the attention reference uses unquantized kn/vn
        orf, _ = paged_decode_attention_step_reference(q, kn, vn, kvd, bt, cl)
        np.testing.assert_allclose(np.asarray(out), np.asarray(orf),
                                   atol=3e-5, rtol=3e-4)
        # the returned pool holds the QUANTIZED new rows: it must dequantize
        # to the reference pool built from dequantized new rows
        knq, kns = kv_quantize_rows(kn)
        vnq, vns = kv_quantize_rows(vn)
        knd = knq.astype(jnp.float32) * kns[..., None]
        vnd = vnq.astype(jnp.float32) * vns[..., None]
        _, kvrf = paged_decode_attention_step_reference(q, knd, vnd, kvd,
                                                        bt, cl)
        kvfd = kvf.astype(jnp.float32) * scf[..., None]
        np.testing.assert_allclose(np.asarray(kvfd), np.asarray(kvrf),
                                   atol=1e-6)

    def test_chunk_matches_dequant_reference(self):
        from deepspeed_tpu.ops.pallas.paged_attention import (
            paged_chunk_attention_batched,
            paged_chunk_attention_batched_reference)
        rng = np.random.RandomState(24)
        NC, Cs, H, Hkv, D, bs, MB = 2, 16, 4, 2, 128, 128, 2
        NB = NC * MB + 1
        kvq, sc, kvd = self._qpages(rng, NB, Hkv, bs, D)
        q = jnp.asarray(rng.randn(NC, Cs, H, D), jnp.float32)
        bt = jnp.asarray(rng.permutation(NB - 1)[:NC * MB].reshape(NC, MB) + 1,
                         jnp.int32)
        q0s = jnp.asarray([0, 100], jnp.int32)
        ctxs = jnp.asarray([16, 116], jnp.int32)
        out = paged_chunk_attention_batched(q, kvq, bt, q0s, ctxs,
                                            kv_scales=sc)
        ref = paged_chunk_attention_batched_reference(q, kvd, bt, q0s, ctxs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=3e-5, rtol=3e-4)


class TestSidebufBatched:
    """SB-batched sidebuf grid (multiple sequences per grid step): ragged
    prefixes across a block, windowed, and int8 variants must all match the
    single-sequence reference."""

    @pytest.mark.parametrize("window", [None, 12])
    def test_batched_matches_reference(self, window):
        from deepspeed_tpu.ops.pallas.paged_attention import (
            paged_decode_attention_sidebuf,
            paged_decode_attention_sidebuf_reference)
        rng = np.random.RandomState(31)
        S, H, Hkv, D, bs, MB, C = 8, 4, 2, 128, 8, 3, 8
        NB = S * MB + 1
        q = jnp.asarray(rng.randn(S, H, D), jnp.float32)
        kv = jnp.asarray(rng.randn(NB, 2, Hkv, bs, D), jnp.float32)
        bt = jnp.asarray(rng.permutation(NB - 1)[:S * MB].reshape(S, MB) + 1,
                         jnp.int32)
        prefix = jnp.asarray([0, 5, 8, 24, 1, 16, 13, 20], jnp.int32)
        sk = jnp.asarray(rng.randn(S, C, Hkv, D), jnp.float32)
        sv = jnp.asarray(rng.randn(S, C, Hkv, D), jnp.float32)
        out = paged_decode_attention_sidebuf(q, kv, bt, prefix, sk, sv, 4,
                                             window=window)
        ref = paged_decode_attention_sidebuf_reference(q, kv, bt, prefix,
                                                       sk, sv, 4,
                                                       window=window)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-4)

    def test_batched_int8_matches_dequant_reference(self):
        from deepspeed_tpu.ops.pallas.paged_attention import (
            kv_quantize_rows, paged_decode_attention_sidebuf,
            paged_decode_attention_sidebuf_reference)
        rng = np.random.RandomState(32)
        S, H, Hkv, D, bs, MB, C = 4, 4, 2, 128, 128, 2, 8
        NB = S * MB + 1
        kv = jnp.asarray(rng.randn(NB, 2, Hkv, bs, D), jnp.float32)
        kvq, sc = kv_quantize_rows(kv)
        kvd = kvq.astype(jnp.float32) * sc[..., None]
        q = jnp.asarray(rng.randn(S, H, D), jnp.float32)
        bt = jnp.asarray(rng.permutation(NB - 1)[:S * MB].reshape(S, MB) + 1,
                         jnp.int32)
        prefix = jnp.asarray([0, 70, 128, 250], jnp.int32)
        sk = jnp.asarray(rng.randn(S, C, Hkv, D), jnp.float32)
        sv = jnp.asarray(rng.randn(S, C, Hkv, D), jnp.float32)
        out = paged_decode_attention_sidebuf(q, kvq, bt, prefix, sk, sv, 5,
                                             kv_scales=sc)
        ref = paged_decode_attention_sidebuf_reference(q, kvd, bt, prefix,
                                                       sk, sv, 5)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=3e-5, rtol=3e-4)


class TestAlibi:
    """ALiBi in the paged kernels (BLOOM serving parity — reference
    csrc/transformer/inference/csrc/softmax.cu applies alibi on the fused
    softmax path). The kernels add slope_h * k_pos; the -slope_h * q_pos
    term is a softmax row constant and cancels."""

    def test_slope_helper_matches_model_slopes(self):
        from deepspeed_tpu.models.decoder import alibi_slopes
        from deepspeed_tpu.ops.pallas.paged_attention import _alibi_slope
        for H in (4, 8, 16, 12, 14):
            got = _alibi_slope(jnp.arange(H, dtype=jnp.float32), H)
            np.testing.assert_allclose(np.asarray(got),
                                       np.asarray(alibi_slopes(H)),
                                       rtol=1e-6)

    @pytest.mark.parametrize("D", [64, 128])
    def test_decode_matches_reference(self, D):
        from deepspeed_tpu.ops.pallas.paged_attention import (
            paged_decode_attention, paged_decode_attention_reference)
        rng = np.random.RandomState(41)
        S, H, Hkv, NB, bs, MB = 3, 8, 2, 20, 8, 4
        q, kv, bt = _setup(rng, S, H, D, Hkv, NB, bs, MB)
        cl = jnp.asarray([1, 9, 30], jnp.int32)
        out = paged_decode_attention(q, kv, bt, cl, alibi=True)
        ref = paged_decode_attention_reference(q, kv, bt, cl, alibi=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-4)

    def test_step_matches_reference(self):
        from deepspeed_tpu.ops.pallas.paged_attention import (
            paged_decode_attention_step, paged_decode_attention_step_reference)
        rng = np.random.RandomState(42)
        S, H, Hkv, D, bs, MB = 2, 4, 2, 128, 8, 3
        NB = S * MB + 1
        kv = jnp.asarray(rng.randn(NB, 2, Hkv, bs, D), jnp.float32)
        q = jnp.asarray(rng.randn(S, H, D), jnp.float32)
        kn = jnp.asarray(rng.randn(S, Hkv, D), jnp.float32)
        vn = jnp.asarray(rng.randn(S, Hkv, D), jnp.float32)
        bt = jnp.asarray(rng.permutation(NB - 1)[:S * MB].reshape(S, MB) + 1,
                         jnp.int32)
        cl = jnp.asarray([6, 17], jnp.int32)
        out, kvf = paged_decode_attention_step(q, kn, vn, kv, bt, cl,
                                               alibi=True)
        orf, kvrf = paged_decode_attention_step_reference(q, kn, vn, kv,
                                                          bt, cl, alibi=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(orf),
                                   atol=2e-5, rtol=2e-4)

    def test_chunk_matches_reference(self):
        from deepspeed_tpu.ops.pallas.paged_attention import (
            paged_chunk_attention_batched,
            paged_chunk_attention_batched_reference)
        rng = np.random.RandomState(43)
        NC, Cs, H, Hkv, D, bs, MB = 2, 16, 8, 2, 64, 8, 6
        NB = NC * MB + 1
        kv = jnp.asarray(rng.randn(NB, 2, Hkv, bs, D), jnp.float32)
        q = jnp.asarray(rng.randn(NC, Cs, H, D), jnp.float32)
        bt = jnp.asarray(rng.permutation(NB - 1)[:NC * MB].reshape(NC, MB) + 1,
                         jnp.int32)
        q0s = jnp.asarray([0, 13], jnp.int32)
        ctxs = jnp.asarray([16, 29], jnp.int32)
        out = paged_chunk_attention_batched(q, kv, bt, q0s, ctxs, alibi=True)
        ref = paged_chunk_attention_batched_reference(q, kv, bt, q0s, ctxs,
                                                      alibi=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-4)

    def test_sidebuf_matches_reference(self):
        from deepspeed_tpu.ops.pallas.paged_attention import (
            paged_decode_attention_sidebuf,
            paged_decode_attention_sidebuf_reference)
        rng = np.random.RandomState(44)
        S, H, Hkv, D, bs, MB, C = 4, 8, 2, 128, 8, 3, 8
        NB = S * MB + 1
        kv = jnp.asarray(rng.randn(NB, 2, Hkv, bs, D), jnp.float32)
        q = jnp.asarray(rng.randn(S, H, D), jnp.float32)
        bt = jnp.asarray(rng.permutation(NB - 1)[:S * MB].reshape(S, MB) + 1,
                         jnp.int32)
        prefix = jnp.asarray([0, 5, bs, 2 * bs + 3], jnp.int32)
        sk = jnp.asarray(rng.randn(S, C, Hkv, D), jnp.float32)
        sv = jnp.asarray(rng.randn(S, C, Hkv, D), jnp.float32)
        out = paged_decode_attention_sidebuf(q, kv, bt, prefix, sk, sv, 5,
                                             alibi=True)
        ref = paged_decode_attention_sidebuf_reference(q, kv, bt, prefix,
                                                       sk, sv, 5, alibi=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-4)


class TestKVQuantizeEdgeCases:
    """kv_quantize_rows / kv_scales_to_tiles edge cases + the pinned
    round-trip error bound — the numeric contract docs/SERVING.md
    "Quantized KV" documents (the rtol tier derives from it)."""

    def test_zero_rows(self):
        from deepspeed_tpu.ops.pallas.paged_attention import kv_quantize_rows
        q, s = kv_quantize_rows(jnp.zeros((0, 3, 16), jnp.float32))
        assert q.shape == (0, 3, 16) and q.dtype == jnp.int8
        assert s.shape == (0, 3)

    def test_all_zero_row_quantizes_to_zero(self):
        from deepspeed_tpu.ops.pallas.paged_attention import (
            kv_dequantize_rows, kv_quantize_rows)
        q, s = kv_quantize_rows(jnp.zeros((2, 16), jnp.float32))
        assert not np.asarray(q).any()
        assert np.isfinite(np.asarray(s)).all()      # the 1e-20 floor holds
        assert not np.asarray(kv_dequantize_rows(q, s)).any()

    def test_single_element_extremes_and_saturation(self):
        from deepspeed_tpu.ops.pallas.paged_attention import kv_quantize_rows
        # one huge element per row: it maps to EXACTLY +-127 (amax/s == 127
        # by construction — no clipping needed), tiny siblings round to 0
        x = np.zeros((2, 128), np.float32)
        x[0, 3] = 3e4
        x[0, 7] = 1e-3
        x[1, 5] = -2e-6
        q, s = kv_quantize_rows(jnp.asarray(x))
        q = np.asarray(q)
        assert q[0, 3] == 127 and q[0, 7] == 0
        assert q[1, 5] == -127                        # row max-abs element
        assert np.abs(q).max() <= 127                 # never overflows int8
        # extreme magnitudes at both ends stay finite
        x2 = np.full((1, 128), 3.0e38, np.float32)
        q2, s2 = kv_quantize_rows(jnp.asarray(x2))
        assert np.isfinite(np.asarray(s2)).all()
        assert (np.asarray(q2) == 127).all()

    def test_roundtrip_error_bound_pinned(self):
        from deepspeed_tpu.ops.pallas.paged_attention import (
            kv_dequantize_rows, kv_quantize_rows)
        rng = np.random.RandomState(0)
        x = (rng.randn(64, 4, 128) * np.exp(rng.randn(64, 4, 1))
             ).astype(np.float32)
        q, s = kv_quantize_rows(jnp.asarray(x))
        deq = np.asarray(kv_dequantize_rows(q, s))
        amax = np.abs(x).max(-1, keepdims=True)
        # |x - deq(q(x))| <= s/2 = amax/254 per element (round-to-nearest
        # of x/s), the bound the rtol gate tier derives from
        assert (np.abs(x - deq) <= amax / 254 * (1 + 1e-5)).all()

    def test_write_dequant_value_idempotent(self):
        # the fused decode paths' invariant: re-quantizing the POOL value
        # reproduces the identical int8 bytes AND the identical scale
        # bytes, so every pool writer — raw-row quantizers (ragged pass,
        # verify step) and deq'd-row re-quantizers (decode step, sidebuf
        # flush) — stores bit-identical pages for the same token. The
        # scale exactness is a property of the amax/127 derivation:
        # s = fl(amax/127) satisfies fl(fl(127*s)/127) == s (verified
        # over 17.7M f32 bit patterns across the exponent range; the
        # div->mul->div composition is idempotent after the first
        # division), and the deq'd row's amax element is exactly
        # fl(127*s) because its max-abs value quantizes to +-127.
        from deepspeed_tpu.ops.pallas.paged_attention import (
            kv_quantize_rows, kv_write_dequant)
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(32, 2, 128).astype(np.float32))
        q1, s1 = kv_quantize_rows(x)
        deq = kv_write_dequant(x)
        q2, s2 = kv_quantize_rows(deq)
        assert np.array_equal(np.asarray(q1), np.asarray(q2))
        assert np.array_equal(np.asarray(s1), np.asarray(s2))

    def test_scales_to_tiles_layout_and_padding(self):
        from deepspeed_tpu.ops.pallas.paged_attention import (
            kv_scale_tiles_shape, kv_scales_to_tiles)
        rng = np.random.RandomState(2)
        # 2*Hkv*bs = 256 scales -> 2 lane rows, padded to the 8-row tile:
        # a NON-multiple-of-8 logical row count (the padding case)
        NB, Hkv, bs = 3, 2, 64
        s = rng.rand(NB, 2, Hkv, bs).astype(np.float32)
        tiles = np.asarray(kv_scales_to_tiles(jnp.asarray(s)))
        assert tiles.shape == kv_scale_tiles_shape(NB, Hkv, bs) == (NB, 8, 128)
        flat = tiles.reshape(NB, -1)
        # flat index kv*Hkv*bs + h*bs + t holds scale [kv, h, t]
        for kv_i in range(2):
            for h in range(Hkv):
                idx = kv_i * Hkv * bs + h * bs + np.arange(bs)
                assert np.array_equal(flat[:, idx], s[:, kv_i, h, :])
        # the padded lanes are zero (DMA-read, multiplied only under masks)
        assert not flat[:, 2 * Hkv * bs:].any()
        # already-tiled input passes through untouched
        assert np.array_equal(
            np.asarray(kv_scales_to_tiles(jnp.asarray(tiles))), tiles)
