"""Autotuning tests.

Parity model: reference ``tests/unit/autotuning/test_autotuning.py`` — tuner
iteration order, candidate enumeration, experiment scoring/feasibility, best
selection, results file.
"""

import json
import os

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.autotuning import (Autotuner, GridSearchTuner,
                                      ModelBasedTuner, RandomTuner, build_tuner)


SPACE = [{"a": 1}, {"a": 2}, {"a": 3}, {"a": 4}]


def test_grid_tuner_order_and_best():
    t = GridSearchTuner(SPACE)
    seen = []
    scores = {1: 5.0, 2: None, 3: 9.0, 4: 1.0}
    while t.has_next():
        c = t.next_trial()
        seen.append(c["a"])
        t.record(c, scores[c["a"]])
    assert seen == [1, 2, 3, 4]
    best, s = t.best()
    assert best == {"a": 3} and s == 9.0


def test_random_tuner_is_permutation():
    t = RandomTuner(SPACE, seed=7)
    seen = []
    while t.has_next():
        c = t.next_trial()
        seen.append(c["a"])
        t.record(c, 1.0)
    assert sorted(seen) == [1, 2, 3, 4] and seen != [1, 2, 3, 4]


def test_model_based_tuner_exploits_neighbourhood():
    space = [{"mb": 1}, {"mb": 2}, {"mb": 4}, {"mb": 32}]
    t = ModelBasedTuner(space)
    c1 = t.next_trial()      # first candidate
    t.record(c1, 10.0)
    c2 = t.next_trial()      # nearest unexplored to best ({mb:1}) -> {mb:2}
    assert c2 == {"mb": 2}
    t.record(c2, 100.0)
    c3 = t.next_trial()      # nearest to new best {mb:2} -> {mb:4}
    assert c3 == {"mb": 4}


def test_build_tuner_validation():
    with pytest.raises(ValueError):
        build_tuner("bogus", SPACE)


def test_autotuner_candidates_from_config_bounds():
    at = Autotuner({
        "train_batch_size": 8,
        "mesh": {"data": -1},
        "autotuning": {"enabled": True,
                       "min_train_micro_batch_size_per_gpu": 1,
                       "max_train_micro_batch_size_per_gpu": 4},
    })
    cands = at.candidates()
    stages = {c["zero_optimization.stage"] for c in cands}
    mbs = {c["train_micro_batch_size_per_gpu"] for c in cands}
    assert stages == {0, 1, 2, 3} and mbs == {1, 2, 4}
    assert len(cands) == 12


def test_autotuner_end_to_end(tmp_path):
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHead
    model = GPT2LMHead(GPT2Config(vocab_size=64, n_positions=16, n_embd=32,
                                  n_layer=2, n_head=2))
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 64, (8, 16)).astype(np.int32)}
    at = Autotuner({
        "train_batch_size": 8,
        "mesh": {"data": -1},
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "autotuning": {"enabled": True, "fast": True,
                       "min_train_micro_batch_size_per_gpu": 1,
                       "max_train_micro_batch_size_per_gpu": 1,
                       "tuner_early_stopping": 10},
    }, tuning_space={"zero_optimization.stage": [0, 1]},
        results_dir=str(tmp_path / "res"))
    best, exps = at.tune(model, batch, compile_only=True)
    assert len(exps) == 2
    feasible = [e for e in exps if e.score is not None]
    assert feasible, [e.error for e in exps]
    assert best is not None and "zero_optimization" in best
    payload = json.load(open(tmp_path / "res" / "autotuning_results.json"))
    assert payload["best_overrides"] is not None
    # memory analysis captured on CPU backend too
    assert any("temp_size_in_bytes" in e.metrics for e in feasible)


def test_autotuner_measured_mode(tmp_path):
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHead
    model = GPT2LMHead(GPT2Config(vocab_size=64, n_positions=16, n_embd=32,
                                  n_layer=1, n_head=2))
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 64, (8, 16)).astype(np.int32)}
    at = Autotuner({
        "train_batch_size": 8,
        "mesh": {"data": -1},
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "autotuning": {"enabled": True, "fast": False},
    }, tuning_space={"zero_optimization.stage": [1],
                     "train_micro_batch_size_per_gpu": [1]},
        results_dir=str(tmp_path / "res"))
    best, exps = at.tune(model, batch, compile_only=False, measure_steps=2)
    assert exps[0].score is not None and exps[0].score > 0
    assert "throughput_samples_per_sec" in exps[0].metrics


def test_measure_compiled_rebinds_donated_engine_state(tmp_path):
    """JL003 regression: the measurement loop donates the probe engine's state
    buffers to the compiled step. Before the fix the engine was left holding
    the donated (freed, on TPU) tree; now it must hold the live
    post-measurement state — observable as the stepped optimizer counter and
    non-deleted leaves."""
    import jax

    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHead
    from deepspeed_tpu.comm.mesh import reset_topology

    model = GPT2LMHead(GPT2Config(vocab_size=64, n_positions=16, n_embd=32,
                                  n_layer=1, n_head=2))
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 64, (8, 16)).astype(np.int32)}
    at = Autotuner({
        "train_batch_size": 8,
        "mesh": {"data": -1},
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "autotuning": {"enabled": True},
    }, results_dir=str(tmp_path / "res"))
    reset_topology()
    probe = at._compile_probe(model, at._apply(
        {"zero_optimization.stage": 1, "train_micro_batch_size_per_gpu": 1}),
        batch)
    steps = 2
    throughput = at._measure_compiled(probe, batch_size=8, steps=steps)
    assert throughput > 0
    eng = probe["engine"]
    # warmup + `steps` measured executions all visible through the engine
    assert int(np.asarray(eng.state["step"])) == steps + 1
    # and no leaf dangles into donated storage (donation is stripped on
    # old-jax CPU, but on TPU these would be freed buffers)
    assert not any(getattr(leaf, "is_deleted", lambda: False)()
                   for leaf in jax.tree_util.tree_leaves(eng.state))
