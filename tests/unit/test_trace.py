"""Span tracer tests (``monitor/trace.py`` + ``scripts/trace_check.py``):
ring bounding, disabled-path no-ops, export schema, lane/thread tracks, the
flight recorder, timer span mode, and the engine integration
(docs/OBSERVABILITY.md)."""

import glob
import importlib.util
import json
import os
import threading
import time

import numpy as np
import pytest

from deepspeed_tpu.monitor.trace import (DEFAULT_RING_SIZE, Tracer, _NOOP,
                                         install_from_env, tracer)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _load_trace_check():
    spec = importlib.util.spec_from_file_location(
        "trace_check", os.path.join(REPO_ROOT, "scripts", "trace_check.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


trace_check = _load_trace_check()


@pytest.fixture(autouse=True)
def _fresh_tracer():
    """The module tracer is process-global: isolate every test."""
    tracer.reset()
    yield
    tracer.reset()


def _span_events(doc):
    return [e for e in doc["traceEvents"] if e.get("ph") == "B"]


def _validate(path):
    """Full trace_check schema pass over one file; returns (events, tracks)
    and asserts no errors."""
    errors = []
    events, tracks = trace_check.check_file(path, errors)
    assert errors == [], errors
    return events, tracks


# --------------------------------------------------------------------------- #
# disabled path
# --------------------------------------------------------------------------- #

def test_disabled_tracer_is_noop(tmp_path):
    assert not tracer.enabled
    # span() hands back ONE shared no-op CM — no per-call allocation
    assert tracer.span("x") is _NOOP
    assert tracer.span("y", lane="l") is _NOOP
    with tracer.span("x"):
        pass
    tracer.add("x", 0.0, 1.0)
    tracer.instant("x")
    tracer.counter("x", 1.0)
    assert tracer.summary() == {}
    assert tracer.export() is None
    assert tracer.crash_dump("nope") is None
    assert not list(tmp_path.iterdir())


def test_install_from_env(tmp_path, monkeypatch):
    monkeypatch.delenv("DSTPU_TRACE", raising=False)
    assert not install_from_env().enabled
    monkeypatch.setenv("DSTPU_TRACE", str(tmp_path))
    monkeypatch.setenv("DSTPU_TRACE_RING", "128")
    tr = install_from_env()
    assert tr.enabled and tr.trace_dir == str(tmp_path)
    assert tr.ring_size == 128
    # idempotent: a second arm (different env) does not reconfigure
    monkeypatch.setenv("DSTPU_TRACE", "/nonexistent")
    assert install_from_env().trace_dir == str(tmp_path)


# --------------------------------------------------------------------------- #
# ring semantics
# --------------------------------------------------------------------------- #

def test_ring_bounds_memory_keeps_newest(tmp_path):
    tracer.configure(trace_dir=str(tmp_path), ring_size=16)
    for i in range(40):
        tracer.add("s", float(i), float(i) + 0.5, i=i)
    count, _total = tracer.summary()["s"]
    assert count == 16
    path = tracer.export()
    events, _ = _validate(path)
    kept = sorted(e["args"]["i"] for e in events if e.get("ph") == "B")
    assert kept == list(range(24, 40))   # the NEWEST 16 survive


def test_ring_size_floor_and_default():
    t = Tracer()
    assert t.ring_size == DEFAULT_RING_SIZE
    t.configure(enabled=True, ring_size=2)
    assert t.ring_size == 16   # floor: a 2-slot flight recorder records noise


# --------------------------------------------------------------------------- #
# export schema: B/E pairing, nesting, tracks
# --------------------------------------------------------------------------- #

def test_export_schema_nested_spans_and_threads(tmp_path):
    tracer.configure(trace_dir=str(tmp_path))
    with tracer.span("outer", lane="train/step", step=3):
        with tracer.span("inner", lane="train/step"):
            time.sleep(0.001)
    t0 = time.perf_counter()
    time.sleep(0.001)
    tracer.add("added", t0, time.perf_counter(), lane="serve/decode")
    tracer.instant("mark", lane="serve/decode")
    tracer.counter("depth", 2.0, lane="serve/decode")

    def worker():
        with tracer.span("work"):
            time.sleep(0.001)

    th = threading.Thread(target=worker, name="dstpu-worker")
    th.start()
    th.join()

    path = tracer.export()
    events, tracks = _validate(path)   # B/E matched, ts monotonic per track
    names = {e["name"] for e in _span_events({"traceEvents": events})}
    assert {"outer", "inner", "added", "work"} <= names
    # lanes AND the worker thread each get their own named track
    assert {"train/step", "serve/decode", "dstpu-worker"} <= set(tracks.values())
    # nesting: B outer precedes B inner, E inner precedes E outer
    order = [(e["ph"], e["name"]) for e in events
             if e.get("name") in ("outer", "inner") and e.get("ph") in "BE"]
    assert order == [("B", "outer"), ("B", "inner"),
                     ("E", "inner"), ("E", "outer")]


def test_same_lane_on_two_threads_gets_two_tracks(tmp_path):
    tracer.configure(trace_dir=str(tmp_path))
    barrier = threading.Barrier(2)

    def worker():
        barrier.wait()
        # overlapping-in-time spans on the SAME lane name from two threads:
        # per-thread lane tids keep each track's B/E stack well-formed
        with tracer.span("chunk", lane="offload/kernel"):
            time.sleep(0.005)

    threads = [threading.Thread(target=worker) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    path = tracer.export()
    events, tracks = _validate(path)
    tids = {e["tid"] for e in events if e.get("ph") == "B"
            and e["name"] == "chunk"}
    assert len(tids) == 2
    assert all(tracks[(os.getpid(), tid)] == "offload/kernel" for tid in tids)


def test_trace_check_flags_broken_traces(tmp_path):
    bad = tmp_path / "trace_bad.json"
    bad.write_text(json.dumps({"traceEvents": [
        {"ph": "B", "name": "a", "pid": 1, "tid": 1, "ts": 10.0},
        {"ph": "E", "name": "MISMATCH", "pid": 1, "tid": 1, "ts": 11.0},
        {"ph": "B", "name": "b", "pid": 1, "tid": 1, "ts": 5.0},  # ts goes back
        {"ph": "B", "name": "unclosed", "pid": 1, "tid": 2, "ts": 1.0},
    ]}))
    errors = []
    trace_check.check_file(str(bad), errors)
    text = "\n".join(errors)
    assert "does not match open" in text
    assert "not monotonic" in text
    assert "unmatched 'B'" in text


# --------------------------------------------------------------------------- #
# flight recorder
# --------------------------------------------------------------------------- #

def test_crash_dump_first_reason_wins(tmp_path):
    tracer.configure(trace_dir=str(tmp_path))
    with tracer.span("final/step"):
        pass
    p1 = tracer.crash_dump("first")
    p2 = tracer.crash_dump("second")
    assert p1 == p2 == str(tmp_path / "trace_crash.json")
    events, _ = _validate(p1)
    names = {e["name"] for e in events}
    assert "final/step" in names
    assert "crash: first" in names and "crash: second" not in names


def test_injected_fault_dumps_flight_recorder(tmp_path):
    from deepspeed_tpu.utils import fault_injection as fi
    tracer.configure(trace_dir=str(tmp_path))
    with tracer.span("train/step", step=7):
        pass
    fi.install(fi.parse_plan("unit.site:at=1:action=raise"))
    try:
        with pytest.raises(fi.InjectedFault):
            fi.maybe_fail("unit.site")
    finally:
        fi.clear()
    crash = tmp_path / "trace_crash.json"
    assert crash.exists()
    events, _ = _validate(str(crash))
    names = {e["name"] for e in events}
    assert "train/step" in names                      # the final steps' spans
    assert any(n.startswith("crash: injected raise at unit.site")
               for n in names)


def test_injected_fault_without_tracing_still_raises(tmp_path):
    from deepspeed_tpu.utils import fault_injection as fi
    fi.install(fi.parse_plan("unit.site2:at=1:action=raise"))
    try:
        with pytest.raises(fi.InjectedFault):
            fi.maybe_fail("unit.site2")
    finally:
        fi.clear()
    assert not (tmp_path / "trace_crash.json").exists()


# --------------------------------------------------------------------------- #
# timer span mode
# --------------------------------------------------------------------------- #

def test_timer_emits_spans_when_tracing(tmp_path):
    from deepspeed_tpu.utils.timer import Timer
    t = Timer("fwd")
    t.start()
    t.stop()
    assert tracer.summary() == {}          # disabled: no span
    tracer.configure(trace_dir=str(tmp_path))
    t.reset()
    t.start()
    time.sleep(0.001)
    t.stop()
    count, total = tracer.summary()["timer/fwd"]
    assert count == 1 and total > 0
    # the span and the timer measured the SAME interval, same clock
    assert total == pytest.approx(t.elapsed(reset=False), rel=1e-6)


# --------------------------------------------------------------------------- #
# engine integration: config-armed tracing, zero behavior change
# --------------------------------------------------------------------------- #

def _tiny_engine(cfg_extra):
    import deepspeed_tpu
    import jax.numpy as jnp

    def model(params, b):
        return jnp.mean((b["x"] @ params["w"]) ** 2)

    params = {"w": np.ones((4, 2), np.float32)}
    cfg = {"train_batch_size": 8,
           "optimizer": {"type": "adamw", "params": {"lr": 1e-3}}}
    cfg.update(cfg_extra)
    engine, *_ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                          config=cfg)
    return engine


def test_engine_traces_train_steps_and_exports(tmp_path):
    engine = _tiny_engine({"monitor": {"trace": {"dir": str(tmp_path),
                                                 "ring_size": 512}}})
    assert tracer.enabled and tracer.trace_dir == str(tmp_path)
    batch = {"x": np.ones((8, 4), np.float32)}
    for _ in range(3):
        engine.train_batch(batch)
    # the stats are per-window aggregations of the SAME measured intervals
    # the timeline shows: counts must agree
    summary = tracer.summary()
    assert summary["train/step"][0] == engine.train_stats.steps == 3
    assert summary["train/step/dispatch"][0] == 3
    assert summary["train/step"][1] >= summary["train/step/dispatch"][1]
    engine.destroy()   # exports
    files = glob.glob(str(tmp_path / "trace_*.json"))
    assert files
    events, tracks = _validate(files[0])
    assert "train/step" in set(tracks.values())


def test_engine_tracing_does_not_change_loss_stream(tmp_path):
    batch = {"x": np.linspace(0, 1, 32, dtype=np.float32).reshape(8, 4)}
    plain = _tiny_engine({})
    losses_plain = [float(plain.train_batch(batch)) for _ in range(3)]
    plain.destroy()
    tracer.reset()
    traced = _tiny_engine({"monitor": {"trace": {"dir": str(tmp_path)}}})
    losses_traced = [float(traced.train_batch(batch)) for _ in range(3)]
    compiles0 = traced.compiles
    traced.train_batch(batch)
    assert traced.compiles == compiles0   # tracing adds no recompiles
    traced.destroy()
    assert losses_traced == losses_plain   # byte-identical stream


def test_zero_duration_span_exports_valid_pairs(tmp_path):
    """Coarse perf_counter ticks can stamp t1 == t0; the export must still
    emit the span's B strictly before its own E (review finding: a
    degenerate span used to sort E-before-B and fail trace_check)."""
    tracer.configure(trace_dir=str(tmp_path))
    t = time.perf_counter()
    tracer.add("zero/a", t, t, lane="l")
    tracer.add("zero/b", t, t, lane="l")       # sibling at the same tick
    with tracer.span("zero/outer", lane="l"):  # nested CMs, possibly 0-dur
        with tracer.span("zero/inner", lane="l"):
            pass
    path = tracer.export()
    _validate(path)   # B/E matched + monotonic per track


def test_dead_thread_rings_are_bounded():
    """Thread churn (per-epoch producers, rebuilt pools) must not grow the
    ring registry without bound; recently-dead threads' spans survive."""
    from deepspeed_tpu.monitor.trace import MAX_DEAD_RINGS
    tracer.configure(enabled=True, ring_size=16)

    def record(i):
        tracer.add(f"churn/{i}", 0.0, 1.0)

    n = MAX_DEAD_RINGS + 20
    for i in range(n):
        th = threading.Thread(target=record, args=(i,))
        th.start()
        th.join()
    # one more registration triggers the prune sweep
    tracer.add("main/span", 0.0, 1.0)
    with tracer._reg_lock:
        n_rings = len(tracer._rings)
    assert n_rings <= MAX_DEAD_RINGS + 2   # bound + live main + slack
    # the NEWEST dead threads' spans are still exportable
    assert f"churn/{n - 1}" in tracer.summary()
