"""Span tracer tests (``monitor/trace.py`` + ``scripts/trace_check.py``):
ring bounding, disabled-path no-ops, export schema, lane/thread tracks, the
flight recorder, timer span mode, and the engine integration
(docs/OBSERVABILITY.md)."""

import glob
import importlib.util
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

from deepspeed_tpu.monitor.trace import (DEFAULT_RING_SIZE, Tracer, _NOOP,
                                         install_from_env, tracer)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO_ROOT, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


trace_check = _load_script("trace_check")


@pytest.fixture(autouse=True)
def _fresh_tracer():
    """The module tracer is process-global: isolate every test."""
    tracer.reset()
    yield
    tracer.reset()


def _span_events(doc):
    return [e for e in doc["traceEvents"] if e.get("ph") == "B"]


def _validate(path):
    """Full trace_check schema pass over one file; returns (events, tracks)
    and asserts no errors."""
    errors = []
    events, tracks, *_ = trace_check.check_file(path, errors)
    assert errors == [], errors
    return events, tracks


# --------------------------------------------------------------------------- #
# disabled path
# --------------------------------------------------------------------------- #

def test_disabled_tracer_is_noop(tmp_path):
    assert not tracer.enabled
    # span() hands back ONE shared no-op CM — no per-call allocation
    assert tracer.span("x") is _NOOP
    assert tracer.span("y", lane="l") is _NOOP
    with tracer.span("x"):
        pass
    tracer.add("x", 0.0, 1.0)
    tracer.instant("x")
    tracer.counter("x", 1.0)
    assert tracer.summary() == {}
    assert tracer.export() is None
    assert tracer.crash_dump("nope") is None
    assert not list(tmp_path.iterdir())


def test_install_from_env(tmp_path, monkeypatch):
    monkeypatch.delenv("DSTPU_TRACE", raising=False)
    assert not install_from_env().enabled
    monkeypatch.setenv("DSTPU_TRACE", str(tmp_path))
    monkeypatch.setenv("DSTPU_TRACE_RING", "128")
    tr = install_from_env()
    assert tr.enabled and tr.trace_dir == str(tmp_path)
    assert tr.ring_size == 128
    # idempotent: a second arm (different env) does not reconfigure
    monkeypatch.setenv("DSTPU_TRACE", "/nonexistent")
    assert install_from_env().trace_dir == str(tmp_path)


# --------------------------------------------------------------------------- #
# ring semantics
# --------------------------------------------------------------------------- #

def test_ring_bounds_memory_keeps_newest(tmp_path):
    tracer.configure(trace_dir=str(tmp_path), ring_size=16)
    for i in range(40):
        tracer.add("s", float(i), float(i) + 0.5, i=i)
    count, _total = tracer.summary()["s"]
    assert count == 16
    path = tracer.export()
    events, _ = _validate(path)
    kept = sorted(e["args"]["i"] for e in events if e.get("ph") == "B")
    assert kept == list(range(24, 40))   # the NEWEST 16 survive


def test_ring_size_floor_and_default():
    t = Tracer()
    assert t.ring_size == DEFAULT_RING_SIZE
    t.configure(enabled=True, ring_size=2)
    assert t.ring_size == 16   # floor: a 2-slot flight recorder records noise


# --------------------------------------------------------------------------- #
# export schema: B/E pairing, nesting, tracks
# --------------------------------------------------------------------------- #

def test_export_schema_nested_spans_and_threads(tmp_path):
    tracer.configure(trace_dir=str(tmp_path))
    with tracer.span("outer", lane="train/step", step=3):
        with tracer.span("inner", lane="train/step"):
            time.sleep(0.001)
    t0 = time.perf_counter()
    time.sleep(0.001)
    tracer.add("added", t0, time.perf_counter(), lane="serve/decode")
    tracer.instant("mark", lane="serve/decode")
    tracer.counter("depth", 2.0, lane="serve/decode")

    def worker():
        with tracer.span("work"):
            time.sleep(0.001)

    th = threading.Thread(target=worker, name="dstpu-worker")
    th.start()
    th.join()

    path = tracer.export()
    events, tracks = _validate(path)   # B/E matched, ts monotonic per track
    names = {e["name"] for e in _span_events({"traceEvents": events})}
    assert {"outer", "inner", "added", "work"} <= names
    # lanes AND the worker thread each get their own named track
    assert {"train/step", "serve/decode", "dstpu-worker"} <= set(tracks.values())
    # nesting: B outer precedes B inner, E inner precedes E outer
    order = [(e["ph"], e["name"]) for e in events
             if e.get("name") in ("outer", "inner") and e.get("ph") in "BE"]
    assert order == [("B", "outer"), ("B", "inner"),
                     ("E", "inner"), ("E", "outer")]


def test_same_lane_on_two_threads_gets_two_tracks(tmp_path):
    tracer.configure(trace_dir=str(tmp_path))
    barrier = threading.Barrier(2)

    def worker():
        barrier.wait()
        # overlapping-in-time spans on the SAME lane name from two threads:
        # per-thread lane tids keep each track's B/E stack well-formed
        with tracer.span("chunk", lane="offload/kernel"):
            time.sleep(0.005)

    threads = [threading.Thread(target=worker) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    path = tracer.export()
    events, tracks = _validate(path)
    tids = {e["tid"] for e in events if e.get("ph") == "B"
            and e["name"] == "chunk"}
    assert len(tids) == 2
    assert all(tracks[(os.getpid(), tid)] == "offload/kernel" for tid in tids)


def test_trace_check_flags_broken_traces(tmp_path):
    bad = tmp_path / "trace_bad.json"
    bad.write_text(json.dumps({"traceEvents": [
        {"ph": "B", "name": "a", "pid": 1, "tid": 1, "ts": 10.0},
        {"ph": "E", "name": "MISMATCH", "pid": 1, "tid": 1, "ts": 11.0},
        {"ph": "B", "name": "b", "pid": 1, "tid": 1, "ts": 5.0},  # ts goes back
        {"ph": "B", "name": "unclosed", "pid": 1, "tid": 2, "ts": 1.0},
    ]}))
    errors = []
    trace_check.check_file(str(bad), errors)
    text = "\n".join(errors)
    assert "does not match open" in text
    assert "not monotonic" in text
    assert "unmatched 'B'" in text


# --------------------------------------------------------------------------- #
# flight recorder
# --------------------------------------------------------------------------- #

def test_crash_dump_first_reason_wins(tmp_path):
    tracer.configure(trace_dir=str(tmp_path))
    with tracer.span("final/step"):
        pass
    p1 = tracer.crash_dump("first")
    p2 = tracer.crash_dump("second")
    assert p1 == p2 == str(tmp_path / "trace_crash.json")
    events, _ = _validate(p1)
    names = {e["name"] for e in events}
    assert "final/step" in names
    assert "crash: first" in names and "crash: second" not in names


def test_injected_fault_dumps_flight_recorder(tmp_path):
    from deepspeed_tpu.utils import fault_injection as fi
    tracer.configure(trace_dir=str(tmp_path))
    with tracer.span("train/step", step=7):
        pass
    fi.install(fi.parse_plan("unit.site:at=1:action=raise"))
    try:
        with pytest.raises(fi.InjectedFault):
            fi.maybe_fail("unit.site")
    finally:
        fi.clear()
    crash = tmp_path / "trace_crash.json"
    assert crash.exists()
    events, _ = _validate(str(crash))
    names = {e["name"] for e in events}
    assert "train/step" in names                      # the final steps' spans
    assert any(n.startswith("crash: injected raise at unit.site")
               for n in names)


def test_injected_fault_without_tracing_still_raises(tmp_path):
    from deepspeed_tpu.utils import fault_injection as fi
    fi.install(fi.parse_plan("unit.site2:at=1:action=raise"))
    try:
        with pytest.raises(fi.InjectedFault):
            fi.maybe_fail("unit.site2")
    finally:
        fi.clear()
    assert not (tmp_path / "trace_crash.json").exists()


# --------------------------------------------------------------------------- #
# timer span mode
# --------------------------------------------------------------------------- #

def test_timer_emits_spans_when_tracing(tmp_path):
    from deepspeed_tpu.utils.timer import Timer
    t = Timer("fwd")
    t.start()
    t.stop()
    assert tracer.summary() == {}          # disabled: no span
    tracer.configure(trace_dir=str(tmp_path))
    t.reset()
    t.start()
    time.sleep(0.001)
    t.stop()
    count, total = tracer.summary()["timer/fwd"]
    assert count == 1 and total > 0
    # the span and the timer measured the SAME interval, same clock
    assert total == pytest.approx(t.elapsed(reset=False), rel=1e-6)


# --------------------------------------------------------------------------- #
# engine integration: config-armed tracing, zero behavior change
# --------------------------------------------------------------------------- #

def _tiny_engine(cfg_extra):
    import deepspeed_tpu
    import jax.numpy as jnp

    def model(params, b):
        return jnp.mean((b["x"] @ params["w"]) ** 2)

    params = {"w": np.ones((4, 2), np.float32)}
    cfg = {"train_batch_size": 8,
           "optimizer": {"type": "adamw", "params": {"lr": 1e-3}}}
    cfg.update(cfg_extra)
    engine, *_ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                          config=cfg)
    return engine


def test_engine_traces_train_steps_and_exports(tmp_path):
    engine = _tiny_engine({"monitor": {"trace": {"dir": str(tmp_path),
                                                 "ring_size": 512}}})
    assert tracer.enabled and tracer.trace_dir == str(tmp_path)
    batch = {"x": np.ones((8, 4), np.float32)}
    for _ in range(3):
        engine.train_batch(batch)
    # the stats are per-window aggregations of the SAME measured intervals
    # the timeline shows: counts must agree
    summary = tracer.summary()
    assert summary["train/step"][0] == engine.train_stats.steps == 3
    assert summary["train/step/dispatch"][0] == 3
    assert summary["train/step"][1] >= summary["train/step/dispatch"][1]
    engine.destroy()   # exports
    files = glob.glob(str(tmp_path / "trace_*.json"))
    assert files
    events, tracks = _validate(files[0])
    assert "train/step" in set(tracks.values())


def test_engine_tracing_does_not_change_loss_stream(tmp_path):
    batch = {"x": np.linspace(0, 1, 32, dtype=np.float32).reshape(8, 4)}
    plain = _tiny_engine({})
    losses_plain = [float(plain.train_batch(batch)) for _ in range(3)]
    plain.destroy()
    tracer.reset()
    traced = _tiny_engine({"monitor": {"trace": {"dir": str(tmp_path)}}})
    losses_traced = [float(traced.train_batch(batch)) for _ in range(3)]
    compiles0 = traced.compiles
    traced.train_batch(batch)
    assert traced.compiles == compiles0   # tracing adds no recompiles
    traced.destroy()
    assert losses_traced == losses_plain   # byte-identical stream


def test_zero_duration_span_exports_valid_pairs(tmp_path):
    """Coarse perf_counter ticks can stamp t1 == t0; the export must still
    emit the span's B strictly before its own E (review finding: a
    degenerate span used to sort E-before-B and fail trace_check)."""
    tracer.configure(trace_dir=str(tmp_path))
    t = time.perf_counter()
    tracer.add("zero/a", t, t, lane="l")
    tracer.add("zero/b", t, t, lane="l")       # sibling at the same tick
    with tracer.span("zero/outer", lane="l"):  # nested CMs, possibly 0-dur
        with tracer.span("zero/inner", lane="l"):
            pass
    path = tracer.export()
    _validate(path)   # B/E matched + monotonic per track


def test_dead_thread_rings_are_bounded():
    """Thread churn (per-epoch producers, rebuilt pools) must not grow the
    ring registry without bound; recently-dead threads' spans survive."""
    from deepspeed_tpu.monitor.trace import MAX_DEAD_RINGS
    tracer.configure(enabled=True, ring_size=16)

    def record(i):
        tracer.add(f"churn/{i}", 0.0, 1.0)

    n = MAX_DEAD_RINGS + 20
    for i in range(n):
        th = threading.Thread(target=record, args=(i,))
        th.start()
        th.join()
    # one more registration triggers the prune sweep
    tracer.add("main/span", 0.0, 1.0)
    with tracer._reg_lock:
        n_rings = len(tracer._rings)
    assert n_rings <= MAX_DEAD_RINGS + 2   # bound + live main + slack
    # the NEWEST dead threads' spans are still exportable
    assert f"churn/{n - 1}" in tracer.summary()


# --------------------------------------------------------------------------- #
# request flow chains: trace_id args -> Perfetto flow events
# --------------------------------------------------------------------------- #

def _flow_events(doc):
    return [e for e in doc["traceEvents"] if e.get("ph") in ("s", "t", "f")]


def _emit_chain(tid, t, lanes=("serve/router", "serve/req/u{}")):
    """One request's hop spans across two lanes, all stamped trace_id."""
    req_lane = lanes[1].format(tid)
    tracer.add("serve/router/route", t, t + 1e-3, lane=lanes[0],
               uid=tid, trace_id=tid)
    tracer.add("serve/req/prefill", t + 1e-3, t + 2e-3, lane=req_lane,
               uid=tid, trace_id=tid)
    tracer.add("serve/req/decode", t + 2e-3, t + 4e-3, lane=req_lane,
               uid=tid, trace_id=tid)


def test_flow_events_bind_hops_across_lanes(tmp_path):
    tracer.configure(trace_dir=str(tmp_path))
    t = time.perf_counter()
    _emit_chain(9, t)
    # single-hop id: a chain needs two ends, so no flow events at all
    tracer.add("serve/req/queued", t, t + 1e-3, lane="serve/req/u8",
               uid=8, trace_id=8)
    path = tracer.export()
    with open(path) as f:
        doc = json.load(f)
    flows = _flow_events(doc)
    assert {e["id"] for e in flows} == {9}
    phs = [e["ph"] for e in sorted(flows, key=lambda e: e["ts"])]
    assert phs == ["s", "t", "f"]       # exactly one s, one f, steps between
    assert all(e["name"] == "serve/req" for e in flows)
    # the finish binds to its ENCLOSING slice, not the next one
    assert [e for e in flows if e["ph"] == "f"][0]["bp"] == "e"
    # the chain crosses lanes: router hop and req-lane hops sit on
    # different tracks
    assert len({e["tid"] for e in flows}) == 2
    # the full schema pass (incl. flow checks: matched s/f, no dangling
    # bindings, steps inside [s, f]) holds
    errors = []
    _events, _tracks, _spans, flow_info = trace_check.check_file(path, errors)
    assert errors == [], errors
    bound_tracks, bound_names = flow_info[9]
    assert len(bound_tracks) >= 2
    assert any(n.startswith("serve/req") for n in bound_names)


def test_trace_check_require_flows_gate(tmp_path, monkeypatch, capsys):
    """--require-flows passes only on a CROSS-LANE chain: a chain confined
    to one lane (or no chain) must fail the gate."""
    cross = tmp_path / "cross"
    flat = tmp_path / "flat"
    for d in (cross, flat):
        d.mkdir()
    tracer.configure(trace_dir=str(cross))
    _emit_chain(3, time.perf_counter())
    tracer.export()
    tracer.reset()
    tracer.configure(trace_dir=str(flat))
    t = time.perf_counter()   # two hops, ONE lane: no cross-lane chain
    tracer.add("serve/req/queued", t, t + 1e-3, lane="serve/req/u1",
               uid=1, trace_id=1)
    tracer.add("serve/req/decode", t + 1e-3, t + 2e-3, lane="serve/req/u1",
               uid=1, trace_id=1)
    tracer.export()
    monkeypatch.setattr(sys, "argv", ["trace_check", str(cross),
                                      "--require-flows", "serve/req"])
    assert trace_check.main() == 0
    monkeypatch.setattr(sys, "argv", ["trace_check", str(flat),
                                      "--require-flows", "serve/req"])
    assert trace_check.main() == 1
    assert "no cross-lane flow chain" in capsys.readouterr().out


def test_trace_check_flags_broken_flows(tmp_path):
    """Dangling s (no f), backwards chains, and non-binding flow events
    are each schema errors."""
    meta = [{"ph": "M", "name": "thread_name", "pid": 1, "tid": 1,
             "args": {"name": "lane"}}]
    span = [{"ph": "B", "name": "serve/req/decode", "pid": 1, "tid": 1,
             "ts": 10.0},
            {"ph": "E", "name": "serve/req/decode", "pid": 1, "tid": 1,
             "ts": 20.0}]

    def _check(events):
        p = tmp_path / "t.json"
        p.write_text(json.dumps({"traceEvents": meta + events}))
        errors = []
        trace_check.check_file(str(p), errors)
        return errors

    fl = {"name": "serve/req", "cat": "flow", "pid": 1, "tid": 1, "id": 4}
    # dangling: an s with no matching f
    errs = _check(span + [dict(fl, ph="s", ts=10.0)])
    assert any("1 's' and 0 'f'" in e for e in errs)
    # backwards: f strictly before s
    errs = _check(span + [dict(fl, ph="f", ts=12.0, bp="e"),
                          dict(fl, ph="s", ts=15.0)])
    assert any("BACKWARDS" in e for e in errs)
    # non-binding: flow event outside every span on its track
    errs = _check(span + [dict(fl, ph="s", ts=10.0),
                          dict(fl, ph="f", ts=99.0, bp="e")])
    assert any("binds to no span" in e for e in errs)


# --------------------------------------------------------------------------- #
# bounded per-request lanes: retired uids recycle onto pooled tracks
# --------------------------------------------------------------------------- #

def test_req_lane_window_recycles_retired_lanes(tmp_path):
    tracer.configure(trace_dir=str(tmp_path), req_lane_window=2)
    base = time.perf_counter()
    for k in range(5):   # disjoint in time: u0 oldest ... u4 newest
        tracer.add("serve/req/decode", base + k, base + k + 0.5,
                   lane=f"serve/req/u{k}", uid=k)
    path = tracer.export()
    events, tracks = _validate(path)   # recycled tracks still nest B/E
    names = set(tracks.values())
    # the newest `window` lanes keep their own named track
    assert {"serve/req/u3", "serve/req/u4"} <= names
    assert not names & {"serve/req/u0", "serve/req/u1", "serve/req/u2"}
    # disjoint retired lanes interval-pack onto ONE pooled track
    assert "serve/req/recycled/0" in names
    assert "serve/req/recycled/1" not in names
    # nothing was dropped: every span survives the remap
    assert len(_span_events({"traceEvents": events})) == 5


def test_req_lane_recycling_never_overlaps_one_track(tmp_path):
    """Time-overlapping retired requests must land on DIFFERENT pooled
    tracks — B/E nesting per track stays well-formed."""
    tracer.configure(trace_dir=str(tmp_path), req_lane_window=0)
    base = time.perf_counter()
    tracer.add("serve/req/decode", base, base + 2.0,
               lane="serve/req/u0", uid=0)
    tracer.add("serve/req/decode", base + 1.0, base + 3.0,   # overlaps u0
               lane="serve/req/u1", uid=1)
    tracer.add("serve/req/decode", base + 2.5, base + 4.0,   # fits after u0
               lane="serve/req/u2", uid=2)
    path = tracer.export()
    events, tracks = _validate(path)   # would fail on an overlapped track
    names = set(tracks.values())
    assert "serve/req/recycled/0" in names and "serve/req/recycled/1" in names
    assert not any(n.startswith("serve/req/u") for n in names)


def test_req_lane_window_env_and_config(tmp_path, monkeypatch):
    from deepspeed_tpu.monitor.trace import DEFAULT_REQ_LANE_WINDOW
    assert tracer.req_lane_window == DEFAULT_REQ_LANE_WINDOW
    monkeypatch.setenv("DSTPU_TRACE", str(tmp_path))
    monkeypatch.setenv("DSTPU_TRACE_REQ_LANES", "7")
    tr = install_from_env()
    assert tr.req_lane_window == 7 and tr.enabled


# --------------------------------------------------------------------------- #
# clock sync + trace_merge: one timeline across processes
# --------------------------------------------------------------------------- #

def test_export_carries_clock_sync_anchor(tmp_path):
    tracer.configure(trace_dir=str(tmp_path))
    tracer.add("x", 0.0, 1.0)
    path = tracer.export()
    with open(path) as f:
        sync = json.load(f)["clockSync"]
    assert sync["pid"] == os.getpid()
    # the anchor really maps perf time onto the wall clock
    off_s = (sync["unix_us"] - sync["perf_us"]) / 1e6
    assert abs(off_s + time.perf_counter() - time.time()) < 5.0


def _fake_trace(path, pid, lane, span_name, sync_unix_us, flow_id,
                ts0=10.0, ts1=20.0):
    """One well-formed single-chain trace file with a clockSync anchor
    (perf epoch 0) — two flow ends so the per-file chain is complete."""
    events = [
        {"ph": "M", "name": "thread_name", "pid": pid, "tid": 1,
         "args": {"name": lane}},
        {"ph": "B", "name": span_name, "pid": pid, "tid": 1, "ts": ts0,
         "args": {"trace_id": flow_id}},
        {"ph": "s", "name": "serve/req", "cat": "flow", "pid": pid,
         "tid": 1, "ts": ts0, "id": flow_id},
        {"ph": "f", "name": "serve/req", "cat": "flow", "pid": pid,
         "tid": 1, "ts": ts1 - 1.0, "id": flow_id, "bp": "e"},
        {"ph": "E", "name": span_name, "pid": pid, "tid": 1, "ts": ts1},
    ]
    with open(path, "w") as f:
        json.dump({"traceEvents": events,
                   "clockSync": {"perf_us": 0.0, "unix_us": sync_unix_us,
                                 "pid": pid}}, f)


def test_trace_merge_clock_aligns_and_stitches(tmp_path):
    """Two files with different perf epochs merge onto one wall-clock axis,
    and a flow id crossing files is stitched into ONE chain (one global s,
    one global f, steps between)."""
    trace_merge = _load_script("trace_merge")
    a, b = str(tmp_path / "trace_1.json"), str(tmp_path / "trace_2.json")
    # same flow id 5: file A's hops are 1s earlier on the wall clock
    _fake_trace(a, pid=1, lane="serve/req/u5", span_name="serve/req/prefill",
                sync_unix_us=1_000_000.0, flow_id=5)
    _fake_trace(b, pid=2, lane="serve/req/u5", span_name="serve/req/decode",
                sync_unix_us=2_000_000.0, flow_id=5)
    doc = trace_merge.merge([a, b])
    ts = [e["ts"] for e in doc["traceEvents"]
          if isinstance(e.get("ts"), (int, float)) and e.get("ph") != "M"]
    assert min(ts) == 0.0                       # rebased near zero
    assert max(ts) == pytest.approx(1_000_010.0)   # the 1s epoch gap survived
    flows = sorted(_flow_events(doc), key=lambda e: e["ts"])
    assert [e["ph"] for e in flows] == ["s", "t", "t", "f"]
    assert flows[0]["pid"] == 1 and flows[-1]["pid"] == 2
    assert flows[-1]["bp"] == "e"
    # the merged doc passes the flow-aware schema checks
    errors = []
    tracks, spans, flows_raw = trace_check.check_events(
        doc["traceEvents"], errors)
    trace_check.check_flows(flows_raw, spans, tracks, errors)
    assert errors == [], errors


def test_trace_merge_cli_output_passes_flow_check(tmp_path, monkeypatch):
    trace_merge = _load_script("trace_merge")
    _fake_trace(str(tmp_path / "trace_1.json"), pid=1, lane="serve/router",
                span_name="serve/router/route", sync_unix_us=0.0, flow_id=7)
    _fake_trace(str(tmp_path / "trace_2.json"), pid=2, lane="serve/req/u7",
                span_name="serve/req/decode", sync_unix_us=500_000.0,
                flow_id=7)
    merged = str(tmp_path / "trace_merged.json")
    monkeypatch.setattr(sys, "argv", ["trace_merge", str(tmp_path),
                                      "-o", merged])
    assert trace_merge.main() == 0
    monkeypatch.setattr(sys, "argv", ["trace_check", merged,
                                      "--require-flows", "serve/req"])
    assert trace_check.main() == 0
    # re-merging skips the merged output itself (no event duplication)
    monkeypatch.setattr(sys, "argv", ["trace_merge", str(tmp_path),
                                      "-o", str(tmp_path / "m2.json")])
    assert trace_merge.main() == 0
    with open(tmp_path / "m2.json") as f:
        doc2 = json.load(f)
    assert sorted(doc2["mergedFrom"]) == ["trace_1.json", "trace_2.json"]


# --------------------------------------------------------------------------- #
# request_autopsy: the offline waterfall + attribution view
# --------------------------------------------------------------------------- #

def test_request_autopsy_smoke_renders_worst_chain(tmp_path, monkeypatch,
                                                   capsys):
    autopsy = _load_script("request_autopsy")
    tracer.configure(trace_dir=str(tmp_path))
    t = time.perf_counter()
    _emit_chain(11, t)                    # 3 hops over ~4 ms
    tracer.add("serve/req/queued", t, t + 1e-4, lane="serve/req/u12",
               uid=12, trace_id=12)       # single-hop: not a chain
    tracer.export()
    monkeypatch.setattr(sys, "argv",
                        ["request_autopsy", str(tmp_path), "--smoke"])
    assert autopsy.main() == 0
    out = capsys.readouterr().out
    assert "trace_id 11" in out           # the worst (only) multi-hop chain
    assert "phase attribution" in out and "dominant phase: decode" in out
    # --trace-id renders a specific chain; unknown ids fail loudly
    monkeypatch.setattr(sys, "argv", ["request_autopsy", str(tmp_path),
                                      "--trace-id", "11"])
    assert autopsy.main() == 0
    monkeypatch.setattr(sys, "argv", ["request_autopsy", str(tmp_path),
                                      "--trace-id", "404"])
    assert autopsy.main() == 1


def test_request_autopsy_smoke_fails_without_chains(tmp_path, monkeypatch):
    autopsy = _load_script("request_autopsy")
    tracer.configure(trace_dir=str(tmp_path))
    tracer.add("train/step", 0.0, 1.0)    # spans, but no trace_id args
    tracer.export()
    monkeypatch.setattr(sys, "argv",
                        ["request_autopsy", str(tmp_path), "--smoke"])
    assert autopsy.main() == 1
