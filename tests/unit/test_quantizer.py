"""Quantizer op tests (parity: reference tests/unit/ops/quantizer)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.quantizer import (quantize, dequantize,
                                         quantize_dequantize, ste_quantize)


def test_int8_roundtrip_error_bounded():
    x = jax.random.normal(jax.random.PRNGKey(0), (64, 256))
    out = quantize_dequantize(x, num_bits=8, group_size=256)
    err = jnp.abs(out - x)
    # max error per group is scale/2 = max|x|/127/2
    assert float(err.max()) < float(jnp.abs(x).max()) / 127.0
    assert out.dtype == x.dtype


def test_int4_coarser_than_int8():
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 512))
    e8 = jnp.abs(quantize_dequantize(x, 8) - x).mean()
    e4 = jnp.abs(quantize_dequantize(x, 4) - x).mean()
    assert float(e4) > float(e8) > 0.0


def test_asymmetric_handles_offset_data():
    x = jax.random.uniform(jax.random.PRNGKey(2), (4, 256)) + 10.0
    sym = quantize_dequantize(x, 8, symmetric=True)
    asym = quantize_dequantize(x, 8, symmetric=False)
    assert float(jnp.abs(asym - x).mean()) < float(jnp.abs(sym - x).mean())


def test_quantize_shapes_and_dtype():
    x = jax.random.normal(jax.random.PRNGKey(3), (8, 128))
    q, s, z = quantize(x, 8, group_size=128)
    assert q.dtype == jnp.int8
    assert q.shape == (8, 128)
    assert s.shape == (8,)
    back = dequantize(q, s, z, x.shape)
    assert back.shape == x.shape


def test_zero_group_safe():
    x = jnp.zeros((2, 256))
    out = quantize_dequantize(x)
    np.testing.assert_allclose(np.asarray(out), 0.0)


def test_ste_gradient_is_identity():
    x = jax.random.normal(jax.random.PRNGKey(4), (256,))
    g = jax.grad(lambda t: jnp.sum(ste_quantize(t) * 3.0))(x)
    np.testing.assert_allclose(np.asarray(g), 3.0, rtol=1e-6)


def test_indivisible_group_raises():
    with pytest.raises(ValueError, match="not divisible"):
        quantize(jnp.ones((3, 100)), group_size=256)
