"""Monitor subsystem tests (parity: ``tests/unit/monitor/test_monitor.py``)."""

import csv
import os

import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.config import DeepSpeedTPUConfig
from deepspeed_tpu.monitor import CsvMonitor, MonitorMaster, TensorBoardMonitor, WandbMonitor


def _cfg(tmp_path, **over):
    d = {"train_batch_size": 8,
         "csv_monitor": {"enabled": True, "output_path": str(tmp_path),
                         "job_name": "job"}}
    d.update(over)
    return DeepSpeedTPUConfig.load(d)


def test_csv_monitor_writes_files(tmp_path):
    cfg = _cfg(tmp_path)
    mon = CsvMonitor(cfg.csv_monitor)
    mon.write_events([("Train/Samples/train_loss", 1.5, 10),
                      ("Train/Samples/train_loss", 1.25, 20),
                      ("Train/Samples/lr", 1e-3, 10)])
    mon.close()
    loss_file = os.path.join(str(tmp_path), "job", "Train_Samples_train_loss.csv")
    assert os.path.exists(loss_file)
    with open(loss_file) as f:
        rows = list(csv.reader(f))
    assert rows[0] == ["step", "value"]
    assert rows[1] == ["10", "1.5"]
    assert rows[2] == ["20", "1.25"]
    assert os.path.exists(os.path.join(str(tmp_path), "job", "Train_Samples_lr.csv"))


def test_monitor_master_fanout_and_gating(tmp_path):
    cfg = _cfg(tmp_path)
    master = MonitorMaster(cfg)
    assert master.enabled
    master.write_events([("Train/Samples/train_loss", 2.0, 1)])
    assert os.path.exists(os.path.join(str(tmp_path), "job",
                                       "Train_Samples_train_loss.csv"))
    # disabled config -> master disabled, write is a no-op
    off = DeepSpeedTPUConfig.load({"train_batch_size": 8})
    master_off = MonitorMaster(off)
    assert not master_off.enabled
    master_off.write_events([("x", 1.0, 1)])


def test_disabled_backends_degrade():
    cfg = DeepSpeedTPUConfig.load({"train_batch_size": 8})
    assert not TensorBoardMonitor(cfg.tensorboard).enabled
    assert not WandbMonitor(cfg.wandb).enabled


def test_engine_writes_monitor_events(tmp_path):
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHead

    model = GPT2LMHead(GPT2Config.tiny())
    cfg = {"train_batch_size": 8,
           "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
           "csv_monitor": {"enabled": True, "output_path": str(tmp_path),
                           "job_name": "engine_job"}}
    engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg)
    batch = {"input_ids": np.zeros((8, 16), np.int32)}
    engine.train_batch(batch)
    loss_file = os.path.join(str(tmp_path), "engine_job",
                             "Train_Samples_train_loss.csv")
    # metrics ride ONE STEP LATE (deferred drain, docs/TRAINING.md): nothing
    # lands until the next step (or an explicit flush) drains step 1
    assert not os.path.exists(loss_file)
    engine.train_batch(batch)
    with open(loss_file) as f:
        rows = list(csv.reader(f))
    assert len(rows) == 2  # header + step 1 (drained while step 2 ran)
    engine.drain_metrics()
    with open(loss_file) as f:
        rows = list(csv.reader(f))
    assert len(rows) == 3  # flush materialised step 2 as well


def test_offload_pipeline_stats_counters_and_events():
    from deepspeed_tpu.monitor import OffloadPipelineStats

    st = OffloadPipelineStats()
    # the add() phase contract shared with HostOffloadOptimizer.step_groups
    st.add("fetch", 0.001)
    st.add("kernel", 0.004)
    st.add("upload", 0.002)
    st.add("swap", 0.010)
    st.record_step(groups=2, depth_sum=1)
    st.add("kernel", 0.004)
    st.record_step(groups=2)
    assert st.steps == 2 and st.groups == 4
    assert st.kernel_ms == pytest.approx(8.0)
    ev = dict((name, val) for name, val, _ in st.events(16))
    assert ev["train/offload/kernel_ms_per_group"] == pytest.approx(2.0)
    assert ev["train/offload/swap_ms_per_step"] == pytest.approx(5.0)
    assert ev["train/offload/groups_per_step"] == pytest.approx(2.0)
    with pytest.raises(KeyError):
        st.add("bogus_phase", 0.1)   # typos must not accumulate silently
    st.reset()
    assert st.steps == 0 and st.kernel_ms == 0.0 and st.upload_depth_sum == 0


def test_monitor_master_rank0_gating(tmp_path, monkeypatch):
    """Only the process-rank-0 host writes; other ranks fan out nothing."""
    import deepspeed_tpu.comm as dist
    monkeypatch.setattr(dist, "get_rank", lambda: 1)
    cfg = _cfg(tmp_path)
    master = MonitorMaster(cfg)
    assert master.enabled          # backends exist; the GATE is per-write
    master.write_events([("Train/Samples/train_loss", 2.0, 1)])
    assert not os.path.exists(os.path.join(str(tmp_path), "job",
                                           "Train_Samples_train_loss.csv"))


def test_tensorboard_degrades_without_wheel(tmp_path, monkeypatch):
    """An enabled tensorboard config on a box without the wheel must degrade
    to a disabled backend (warning, no raise) — the least-tested path in the
    module and exactly what this container exercises in prod."""
    import sys
    # None in sys.modules makes `from torch.utils.tensorboard import ...`
    # raise ImportError deterministically, wheel or no wheel
    monkeypatch.setitem(sys.modules, "torch", None)
    monkeypatch.setitem(sys.modules, "torch.utils.tensorboard", None)
    cfg = _cfg(tmp_path, tensorboard={"enabled": True,
                                      "output_path": str(tmp_path),
                                      "job_name": "tb"})
    tb = TensorBoardMonitor(cfg.tensorboard)
    assert not tb.enabled
    tb.write_events([("x", 1.0, 1)])   # disabled backend: no-op, no raise
    tb.close()                         # close on a degraded backend: no-op
    # the master stays usable through its OTHER backends
    master = MonitorMaster(cfg)
    assert master.enabled and not master.tb_monitor.enabled
    master.write_events([("Train/Samples/train_loss", 1.0, 1)])
    assert os.path.exists(os.path.join(str(tmp_path), "job",
                                       "Train_Samples_train_loss.csv"))


def test_monitor_master_fanout_ordering(tmp_path):
    """Backends receive the SAME event list, in tb -> wandb -> csv order,
    with intra-list event order preserved."""
    cfg = _cfg(tmp_path)
    master = MonitorMaster(cfg)
    calls = []

    class Recorder:
        def __init__(self, name):
            self.name = name
            self.enabled = True

        def write_events(self, events):
            calls.append((self.name, list(events)))

        def close(self):
            calls.append((self.name, "closed"))

    master.tb_monitor = Recorder("tb")
    master.wandb_monitor = Recorder("wandb")
    master.csv_monitor = Recorder("csv")
    events = [("a", 1.0, 1), ("b", 2.0, 1), ("a", 3.0, 2)]
    master.write_events(iter(events))   # an iterator must fan out to ALL
    assert [name for name, _ in calls] == ["tb", "wandb", "csv"]
    assert all(got == events for _, got in calls)
    calls.clear()
    master.close()
    assert calls == [("tb", "closed"), ("wandb", "closed"), ("csv", "closed")]


def test_monitor_master_close_closes_csv_files(tmp_path):
    cfg = _cfg(tmp_path)
    master = MonitorMaster(cfg)
    master.write_events([("Train/Samples/train_loss", 1.0, 1)])
    assert master.csv_monitor._files
    master.close()
    assert master.csv_monitor._files == {}
    master.close()   # idempotent


def test_engine_destroy_flushes_final_step_without_manual_drain(tmp_path):
    """The PR 4 footgun, closed: the LAST step's deferred metrics land in
    the CSV through ``destroy()`` alone — no ``drain_metrics()`` call — and
    the backend files are closed behind it."""
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHead

    model = GPT2LMHead(GPT2Config.tiny())
    cfg = {"train_batch_size": 8,
           "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
           "csv_monitor": {"enabled": True, "output_path": str(tmp_path),
                           "job_name": "flush_job"}}
    engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg)
    batch = {"input_ids": np.zeros((8, 16), np.int32)}
    engine.train_batch(batch)
    engine.train_batch(batch)
    loss_file = os.path.join(str(tmp_path), "flush_job",
                             "Train_Samples_train_loss.csv")
    engine.destroy()
    with open(loss_file) as f:
        rows = list(csv.reader(f))
    assert len(rows) == 3          # header + BOTH steps (incl. the final one)
    assert engine.monitor.csv_monitor._files == {}


def test_engine_emits_offload_events_at_print_boundary(tmp_path):
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHead

    model = GPT2LMHead(GPT2Config.tiny())
    cfg = {"train_batch_size": 8, "steps_per_print": 1,
           "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
           "zero_optimization": {"stage": 1,
                                 "offload_optimizer": {"device": "cpu"}},
           "csv_monitor": {"enabled": True, "output_path": str(tmp_path),
                           "job_name": "off_job"}}
    engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg)
    batch = {"input_ids": np.zeros((8, 16), np.int32)}
    engine.train_batch(batch)
    engine.train_batch(batch)
    engine.drain_metrics()
    kernel_file = os.path.join(str(tmp_path), "off_job",
                               "train_offload_kernel_ms_per_group.csv")
    assert os.path.exists(kernel_file)
    with open(kernel_file) as f:
        rows = list(csv.reader(f))
    assert len(rows) >= 2   # header + at least one printed boundary
    engine.destroy()
