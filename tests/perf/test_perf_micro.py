"""Micro-benchmarks (parity: reference ``tests/perf`` — e.g. the CPU-Adam
perf test) run as smoke tests: they assert generous floors so CI catches
order-of-magnitude regressions without being timing-flaky."""

import time

import numpy as np

from deepspeed_tpu.ops.native.cpu_optimizer import HostAdam
from deepspeed_tpu.ops.native.aio import AsyncIOHandle, aligned_empty


def test_host_adam_throughput():
    n = 4_000_000
    p = np.random.rand(n).astype(np.float32)
    g = np.random.rand(n).astype(np.float32)
    m = np.zeros(n, np.float32)
    v = np.zeros(n, np.float32)
    opt = HostAdam(lr=1e-3)
    opt.step(1, p, g, m, v)  # warmup
    t0 = time.perf_counter()
    steps = 5
    for i in range(steps):
        opt.step(i + 2, p, g, m, v)
    dt = (time.perf_counter() - t0) / steps
    params_per_sec = n / dt
    # reference CPU Adam does ~1e8-1e9 params/s with AVX; floor at 2e7
    assert params_per_sec > 2e7, f"{params_per_sec:.2e} params/s"


def test_aio_write_read_bandwidth(tmp_path):
    h = AsyncIOHandle(block_size=1 << 20, thread_count=4)
    try:
        arr = aligned_empty(32 << 20 >> 2, np.float32)  # 32 MiB
        arr[...] = 1.0
        path = str(tmp_path / "bw.bin")
        t0 = time.perf_counter()
        assert h.async_pwrite(arr, path) == 0
        assert h.wait() == 1
        w_bw = arr.nbytes / (time.perf_counter() - t0)
        out = aligned_empty(arr.shape, np.float32)
        t0 = time.perf_counter()
        assert h.async_pread(out, path) == 0
        assert h.wait() == 1
        r_bw = arr.nbytes / (time.perf_counter() - t0)
        np.testing.assert_array_equal(out[:16], arr[:16])
        # floors far below any real disk (tmpfs/page cache typically GB/s)
        assert w_bw > 20e6 and r_bw > 20e6, (w_bw, r_bw)
    finally:
        h.close()
