"""Training step-loop benchmark: synchronous loop vs the async pipeline.

Parity role: the serving side has ``serving_bench.py --steady-state`` holding
the decode pipeline's overlap honest; this is the same harness for the
TRAINING hot path (the ROADMAP's core workload). Two workload legs, each
driving the SAME engine over the SAME data order through two orchestrations:

- **sync**: the pre-PR step loop — the dataloader collates the global batch
  item-by-item on the caller's thread, ``train_batch`` stages it inline
  (host->device ``device_put`` on the critical path), and the loss is
  ``float()``'d immediately, blocking on the just-dispatched step. One full
  serialisation per step.
- **pipelined**: ``PrefetchLoader`` stages device-resident sharded batches
  from a producer thread and ``engine.train_steps`` keeps dispatching fused
  steps while metrics ride one step behind, materialised once at the end.

Legs:

- ``lm``: tiny GPT2 over text items TOKENIZED IN COLLATE (a pure-python
  byte-BPE stand-in for the real tokenizers that run in input pipelines) —
  pad + shifted labels + mask. On a 2-core CPU box the producer's python
  shares the GIL with the consumer, so the overlap win here is modest
  (~1.2x); on a real TPU host the device side costs no host CPU at all and
  the full producer/consumer overlap applies.
- ``host_bound``: the input-bandwidth-bound regime prefetch pipelines exist
  for (t5x prefetch-to-device, tf.data) — feature batches (``[seq, feat]``
  float32 items) whose collate+staging is C-level memcpy comparable to the
  cheap device step. This is the acceptance-gate leg: the host work is
  GIL-free, so the producer genuinely overlaps the device and the pipeline
  clears >=1.3x on the 2-core container.
- ``offload_cpu`` / ``offload_nvme`` (``--offload``): the OFFLOADED
  OPTIMIZER pipeline (docs/TRAINING.md "Offloaded optimizer pipeline").
  Param-heavy/flops-light model (the ZeRO-Offload regime) driven through
  the SAME engine twice per rep: ``overlap_step`` flipped OFF (the pre-PR
  serial fetch-all/step-all/upload-all host step) vs ON (the three-stage
  fetch/step/upload group pipeline, threaded host kernel, NVMe swapper
  double-buffering underneath). Same gates: byte-identical per-step loss
  streams (host kernels are elementwise; the device program is shared, so
  equality is structural — a pipeline bug breaks it) and zero timed-run
  compiles. The nvme leg additionally reports ``swap_ms_per_step`` — the
  pure IO cost that bounds how much slower than the cpu leg it may run.

Correctness gates on BOTH legs (exit 1 on violation — throughput is
reported, the >=1.3x bar applies to the host_bound leg's median):

- per-step loss streams BYTE-IDENTICAL between the orchestrations (same
  math, different orchestration; engine state is snapshot/restored between
  legs so every run starts from the same parameters), and stable across
  repeats;
- zero XLA compiles during the timed runs (``engine.compiles``; warmup
  rounds pay them).

Usage:
  python benchmarks/train_bench.py [--steps 30] [--reps 3] [--smoke]
                                   [--legs lm,host_bound] [--prefetch 2]
"""

from __future__ import annotations

import argparse
import gc
import json
import os
import sys
import time

import numpy as np

# runnable as `python benchmarks/train_bench.py` from a bare checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LM_SEQ = 32
LM_VOCAB = 256
_TEXT = "the quick brown fox jumps over the lazy dog " * 8


def _bpe_ish(text: str):
    """Pure-python byte-pair-ish tokenizer: three greedy merge rounds over
    the utf-8 bytes. A stand-in for the per-item python cost (HF tokenizers,
    augmentation) real input pipelines pay on the caller's thread."""
    toks = list(text.encode("utf-8"))
    for _ in range(3):
        out, i, n = [], 0, len(toks)
        while i < n:
            if i + 1 < n and (toks[i] * 31 ^ toks[i + 1]) % 7 == 0:
                out.append((toks[i] * 31 + toks[i + 1]) % LM_VOCAB)
                i += 2
            else:
                out.append(toks[i] % LM_VOCAB)
                i += 1
        toks = out
    return toks


def lm_collate(items):
    """Tokenize + pad + shifted labels + mask — the LM input pipeline."""
    ids = np.zeros((len(items), LM_SEQ), np.int32)
    labels = np.zeros((len(items), LM_SEQ), np.int32)
    mask = np.zeros((len(items), LM_SEQ), np.int32)
    for i, it in enumerate(items):
        toks = np.asarray(_bpe_ish(it["text"])[:LM_SEQ], np.int32)
        n = len(toks)
        ids[i, :n] = toks
        labels[i, :n] = toks
        mask[i, :n] = 1
    return {"input_ids": ids, "labels": labels, "attention_mask": mask}


def build_lm_leg(on_tpu: bool):
    import jax
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHead

    batch = 64
    if on_tpu:
        cfg_m = GPT2Config(vocab_size=LM_VOCAB, n_positions=128,
                           n_embd=768, n_layer=12, n_head=12)
    else:
        cfg_m = GPT2Config(vocab_size=LM_VOCAB, n_positions=128,
                           n_embd=16, n_layer=1, n_head=2)
    model = GPT2LMHead(cfg_m)
    params = model.init(jax.random.PRNGKey(0),
                        {"input_ids": np.zeros((2, LM_SEQ), np.int32)})["params"]
    engine = _make_engine(model, params, batch)
    rng = np.random.default_rng(0)
    data = [{"text": _TEXT[:int(rng.integers(60, len(_TEXT)))]}
            for _ in range(2 * batch)]
    return engine, data, lm_collate, {"leg": "lm", "batch": batch,
                                      "seqlen": LM_SEQ}


def build_host_bound_leg(on_tpu: bool):
    """Feature-regression workload: collate+staging moves megabytes per step
    (C-level, GIL-free) while the model reduces them cheaply — the
    input-bandwidth-bound regime the prefetch pipeline targets."""
    import jax.numpy as jnp

    batch, seq, feat = 64, 128, 256

    def model(params, b):
        h = jnp.mean(b["x"], axis=1) @ params["w1"]
        pred = jnp.tanh(h) @ params["w2"]
        return jnp.mean((pred - b["y"]) ** 2)

    rng = np.random.default_rng(0)
    params = {"w1": rng.standard_normal((feat, 64)).astype(np.float32) * 0.05,
              "w2": rng.standard_normal((64, 16)).astype(np.float32) * 0.05}
    engine = _make_engine(model, params, batch)
    data = [{"x": rng.standard_normal((seq, feat)).astype(np.float32),
             "y": rng.standard_normal((16,)).astype(np.float32)}
            for _ in range(2 * batch)]
    return engine, data, None, {"leg": "host_bound", "batch": batch,
                                "item_bytes": seq * feat * 4}


def _make_engine(model, params, batch):
    import deepspeed_tpu
    cfg = {"train_batch_size": batch,
           "steps_per_print": 0,
           "optimizer": {"type": "adamw", "params": {"lr": 1e-3}}}
    engine, *_ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                          config=cfg)
    return engine


# --------------------------------------------------------------------------- #
# offload legs (--offload): serial host step vs the fetch/step/upload pipeline
# --------------------------------------------------------------------------- #

def build_offload_leg(on_tpu: bool, smoke: bool, nvme_dir=None):
    """Param-heavy / flops-light workload: most leaves only feed a cheap
    mean-square regulariser, so their grads are full-size but the device
    step is a pass or two — the host optimizer is the step's centre of
    gravity, exactly the regime ZeRO-Offload targets."""
    import jax.numpy as jnp

    batch, feat, hidden = 16, 256, 64
    # full size: 4 x 2M-element wide leaves (8.4M params, ~34 MB fp32
    # masters) — large enough that the host kernel+upload dominate the step
    # (the ZeRO-Offload regime) and each group's kernel can hide its
    # neighbour's upload; smaller sizes drown the overlap in the device
    # step's fixed cost on a 2-core CPU box
    n_wide, wide = (4, 1 << 16) if smoke else (4, 1 << 21)

    def model(params, b):
        h = jnp.tanh(jnp.mean(b["x"], axis=1) @ params["w1"])
        pred = h @ params["w2"]
        loss = jnp.mean((pred - b["y"]) ** 2)
        reg = sum(jnp.mean(params[f"u{i}"] ** 2) for i in range(n_wide))
        return loss + 1e-4 * reg

    rng = np.random.default_rng(0)
    params = {"w1": rng.standard_normal((feat, hidden)).astype(np.float32) * .05,
              "w2": rng.standard_normal((hidden, 16)).astype(np.float32) * .05}
    for i in range(n_wide):
        params[f"u{i}"] = rng.standard_normal(wide).astype(np.float32) * .05

    import deepspeed_tpu
    off = {"device": "cpu", "buffer_count": 2}
    if nvme_dir is not None:
        off.update({"device": "nvme", "nvme_path": nvme_dir,
                    "pipeline_read": True, "pipeline_write": True})
    cfg = {"train_batch_size": batch, "steps_per_print": 0,
           "zero_optimization": {"stage": 1, "offload_optimizer": off},
           "optimizer": {"type": "adamw", "params": {"lr": 1e-3}}}
    engine, *_ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                          config=cfg)
    batches = [{"x": rng.standard_normal((batch, 8, feat)).astype(np.float32),
                "y": rng.standard_normal((batch, 16)).astype(np.float32)}
               for _ in range(4)]
    n_params = sum(int(np.prod(v.shape)) for v in params.values())
    return engine, batches, {
        "leg": "offload_nvme" if nvme_dir else "offload_cpu",
        "batch": batch, "params": n_params,
        "host_groups": len(engine._offload_groups),
        "host_kernel": engine._offload.kernel.backend,
        "host_workers": engine._offload._workers}


def snapshot_offload(engine):
    import jax
    master, moments = engine._offload.state_leaves()
    host = ({k: np.array(v, np.float32) for k, v in master.items()},
            {sk: {k: np.array(v, np.float32) for k, v in d.items()}
             for sk, d in moments.items()},
            engine._offload.step_num)
    return (jax.device_get(engine.state), host, engine.global_steps,
            engine.global_samples, engine.micro_steps)


def restore_offload(engine, snap):
    import jax
    state, (master, moments, step_num), steps, samples, micro = snap
    engine.state = jax.device_put(state, engine._state_shardings)
    engine._offload.load_master_leaves(master)
    engine._offload.load_moment_leaves(moments, step_num=step_num)
    engine.global_steps = steps
    engine.global_samples = samples
    engine.micro_steps = micro
    engine._pending_metrics.clear()
    engine._last_metrics = {}


def offload_run(engine, batches, n: int, overlap: bool):
    """n steps through the SAME engine, host step orchestration selected by
    ``overlap_step`` (the device program and the kernel math are shared —
    only the overlap differs)."""
    engine._offload_cfg.overlap_step = overlap
    losses = []
    gc.disable()
    t0 = time.time()
    for i in range(n):
        losses.append(float(engine.train_batch(batches[i % len(batches)])))
    wall = time.time() - t0
    gc.enable()
    return losses, wall


def run_offload_leg(on_tpu: bool, steps: int, reps: int, smoke: bool,
                    nvme_dir=None):
    engine, batches, info = build_offload_leg(on_tpu, smoke, nvme_dir)
    snap = snapshot_offload(engine)
    warm = max(2, min(4, steps))
    for overlap in (False, True):   # warm both orchestrations + the merge jit
        offload_run(engine, batches, warm, overlap)
        restore_offload(engine, snap)

    c0 = engine.compiles
    speedups, sync_walls, pipe_walls = [], [], []
    equal, first_losses = True, None
    phase = {"steps": 0, "groups": 0, "fetch": 0.0, "kernel": 0.0,
             "upload": 0.0, "swap": 0.0, "depth": 0}
    for _ in range(reps):
        losses_s, wall_s = offload_run(engine, batches, steps, overlap=False)
        restore_offload(engine, snap)
        engine.offload_stats.reset()   # phase breakdown: pipelined runs only
        losses_p, wall_p = offload_run(engine, batches, steps, overlap=True)
        st = engine.offload_stats
        phase["steps"] += st.steps
        phase["groups"] += st.groups
        phase["fetch"] += st.fetch_ms
        phase["kernel"] += st.kernel_ms
        phase["upload"] += st.upload_ms
        phase["swap"] += st.swap_ms
        phase["depth"] += st.upload_depth_sum
        restore_offload(engine, snap)
        equal = equal and losses_p == losses_s
        if first_losses is None:
            first_losses = losses_s
        equal = equal and losses_s == first_losses
        speedups.append(wall_s / wall_p)
        sync_walls.append(wall_s)
        pipe_walls.append(wall_p)
    n = max(1, phase["steps"])
    g = max(1, phase["groups"])
    med = int(np.argsort(speedups)[len(speedups) // 2])
    out = dict(info)
    out.update({
        "steps": steps, "reps": reps,
        "sync_steps_per_sec": round(steps / sync_walls[med], 2),
        "pipelined_steps_per_sec": round(steps / pipe_walls[med], 2),
        "speedup": round(float(np.median(speedups)), 2),
        "speedup_reps": [round(float(s), 2) for s in speedups],
        "losses_equal": bool(equal),
        "compiles_during_timed_runs": engine.compiles - c0,
        "fetch_ms_per_group": round(phase["fetch"] / g, 3),
        "kernel_ms_per_group": round(phase["kernel"] / g, 3),
        "upload_ms_per_group": round(phase["upload"] / g, 3),
        "swap_ms_per_step": round(phase["swap"] / n, 3),
        "upload_depth_per_group": round(phase["depth"] / g, 3),
    })
    engine.destroy()
    del engine
    gc.collect()
    return out


# --------------------------------------------------------------------------- #
# preemption tolerance (--preempt): kill-and-resume onto a different device
# count (docs/ELASTICITY.md). Subprocess workers so a mid-step/mid-write KILL
# (os._exit via DSTPU_FAULTS) is a real process death: no atexit, no finally.
# --------------------------------------------------------------------------- #

# shared elastic schema: final global batch is world-size-INDEPENDENT, so a
# resume at M != N devices trains on the identical per-step global batch
PREEMPT_ELASTIC = {"enabled": True, "max_train_batch_size": 32,
                   "micro_batch_sizes": [4, 8], "min_gpus": 1, "max_gpus": 8,
                   "version": 0.2}
PREEMPT_FEAT, PREEMPT_SEQ, PREEMPT_OUT = 32, 4, 8
PREEMPT_EVERY = 3            # rolling cadence (steps)
PREEMPT_KILL_STEP = 8        # NOT a multiple of the cadence — a mid-run death
PREEMPT_TAG_PREFIX = "rolling_step"


def _preempt_batch(step: int, global_batch: int):
    """The step's global batch, keyed by step index ONLY — every world size
    and every resume sees byte-identical data for step k."""
    rng = np.random.default_rng(10_000 + step)
    return {"x": rng.standard_normal(
                (global_batch, PREEMPT_SEQ, PREEMPT_FEAT)).astype(np.float32),
            "y": rng.standard_normal(
                (global_batch, PREEMPT_OUT)).astype(np.float32)}


def preempt_worker(args):
    """One training run in THIS process: data-parallel over however many
    devices XLA_FLAGS forced, rolling checkpoints on a cadence, optional
    resume from a universal checkpoint (different-world path) or a regular
    tag (the verified-load control). Writes a JSON report to --out."""
    import jax
    import deepspeed_tpu
    from deepspeed_tpu.checkpoint.universal import load_universal_into_engine
    from deepspeed_tpu.elasticity import compute_elastic_config
    from deepspeed_tpu.utils.compile_cache import setup_compile_cache

    setup_compile_cache(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    world = jax.device_count()
    final_batch, _valid, micro = compute_elastic_config(
        {"elasticity": PREEMPT_ELASTIC}, world_size=world,
        return_microbatch=True)
    gas = final_batch // (micro * world)

    import jax.numpy as jnp

    def model(params, b):
        h = jnp.tanh(jnp.mean(b["x"], axis=1) @ params["w1"])
        pred = h @ params["w2"]
        return jnp.mean((pred - b["y"]) ** 2)

    rng = np.random.default_rng(0)
    params = {"w1": rng.standard_normal(
                  (PREEMPT_FEAT, 16)).astype(np.float32) * 0.05,
              "w2": rng.standard_normal(
                  (16, PREEMPT_OUT)).astype(np.float32) * 0.05}
    cfg = {"train_batch_size": final_batch,
           "train_micro_batch_size_per_gpu": micro,
           "mesh": {"data": -1}, "steps_per_print": 0,
           "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
           "checkpoint": {"engine": "async", "writers": 2,
                          "verify_load": True,
                          "rolling": {"every_n_steps": PREEMPT_EVERY,
                                      "save_dir": args.save_dir,
                                      "keep_last": 8, "max_pending": 2}}}
    engine, *_ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                          config=cfg)
    resume_tag = None
    if args.resume_universal:
        load_universal_into_engine(engine, args.resume_universal)
        resume_tag = "universal"
    elif args.resume_tag:
        engine.load_checkpoint(args.load_dir, tag=args.resume_tag, verify=True)
        resume_tag = args.resume_tag
    start_step = engine.global_steps

    losses = {}
    compiles_warm = None
    for step in range(start_step, args.total_steps):
        loss = float(engine.train_batch(_preempt_batch(step, final_batch)))
        losses[str(step + 1)] = loss
        if step == start_step:
            # the first (re)started step pays the (re)compile; everything
            # after must hit the executable cache — the zero-recompile gate
            compiles_warm = engine.compiles
    out = {"world": world, "micro": micro, "gas": gas,
           "global_batch": final_batch, "start_step": start_step,
           "resume_tag": resume_tag, "losses": losses,
           "compiles_after_warmup":
               (engine.compiles - compiles_warm)
               if compiles_warm is not None else 0,
           "ckpt_saves": engine.ckpt_stats.saves}
    engine.destroy()   # flushes rolling commits + closes the async writers
    with open(args.out, "w") as f:
        json.dump(out, f)


def _spawn_preempt_worker(devices: int, total_steps: int, save_dir: str,
                          out_path: str, faults: str = "",
                          resume_universal: str = "", load_dir: str = "",
                          resume_tag: str = ""):
    import subprocess
    env = dict(os.environ)
    env.pop("DSTPU_FAULTS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={devices}"
    if faults:
        env["DSTPU_FAULTS"] = faults
    cmd = [sys.executable, os.path.abspath(__file__), "--preempt-worker",
           "--devices", str(devices), "--total-steps", str(total_steps),
           "--save-dir", save_dir, "--out", out_path]
    if resume_universal:
        cmd += ["--resume-universal", resume_universal]
    if resume_tag:
        cmd += ["--load-dir", load_dir, "--resume-tag", resume_tag]
    return subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=600)


def _read_report(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def run_preempt_leg(total_steps: int) -> bool:
    """Kill at a non-checkpoint step AND mid-checkpoint-write; resume each
    onto a DIFFERENT simulated device count; gate byte-identical loss streams
    (resumed vs an uninterrupted verified-load run from the same surviving
    checkpoint), the global-batch invariant, zero post-warmup recompiles, and
    loss-curve continuity vs the uninterrupted original-world run."""
    import tempfile
    from deepspeed_tpu.checkpoint.state import find_resume_tag, tag_problem
    from deepspeed_tpu.checkpoint.universal import ds_to_universal
    from deepspeed_tpu.utils.fault_injection import KILL_EXIT_CODE

    N, M = 4, 2
    ok = True
    with tempfile.TemporaryDirectory() as td:
        # uninterrupted reference at the ORIGINAL world size (also proves a
        # full rolling run commits every cadence point and prunes cleanly)
        ref_out = os.path.join(td, "ref.json")
        p = _spawn_preempt_worker(N, total_steps, os.path.join(td, "ref"),
                                  ref_out)
        if p.returncode != 0:
            print(json.dumps({"leg": "preempt", "error": "ref run failed",
                              "stderr": p.stderr[-2000:]}), flush=True)
            return False
        ref = _read_report(ref_out)

        # the second spec on step.kill stalls EVERY step 250 ms (the kill spec
        # is listed first, so the kill still wins at its hit): on this box the
        # tiny steps outrun the background committer, and a kill landing
        # before the previous cadence tag committed would leave nothing to
        # resume from — which is a valid preemption outcome, but not the one
        # these legs exist to gate. Real steps are >> commit latency.
        pace = "step.kill:every=1:action=stall:delay_s=0.25"
        legs = {
            # dies between steps: the surviving checkpoint is a committed
            # cadence tag strictly older than the kill step
            "kill_step":
                f"step.kill:at={PREEMPT_KILL_STEP}:action=kill;{pace}",
            # dies INSIDE a rolling tag's npz write (hit 3 = the second
            # cadence save's first file): that tag must be detected as torn
            # and resume must fall back to the previous complete tag
            "kill_write": f"ckpt.writer:at=3:action=kill;{pace}",
        }
        for name, plan in legs.items():
            save_dir = os.path.join(td, name)
            res = {"leg": f"preempt_{name}", "orig_world": N,
                   "resume_world": M}
            p = _spawn_preempt_worker(N, total_steps, save_dir,
                                      os.path.join(td, f"{name}_a.json"),
                                      faults=plan)
            res["killed_with_injection_exit"] = p.returncode == KILL_EXIT_CODE
            tag = find_resume_tag(save_dir)
            res["resume_tag"] = tag
            surviving_ok = (
                tag is not None and tag.startswith(PREEMPT_TAG_PREFIX)
                and tag_problem(save_dir, tag) is None)
            k = int(tag[len(PREEMPT_TAG_PREFIX):]) if surviving_ok else -1
            res["resume_step"] = k
            surviving_ok = surviving_ok and 0 < k < PREEMPT_KILL_STEP \
                and k % PREEMPT_EVERY == 0
            if name == "kill_write":
                # the torn tag is still on disk — and is NOT the one chosen
                torn = os.path.join(save_dir,
                                    f"{PREEMPT_TAG_PREFIX}{2 * PREEMPT_EVERY}")
                res["torn_tag_present"] = os.path.isdir(torn)
                res["torn_tag_detected"] = tag_problem(
                    save_dir, os.path.basename(torn)) is not None
                surviving_ok = surviving_ok and res["torn_tag_present"] \
                    and res["torn_tag_detected"] \
                    and k == PREEMPT_EVERY
            res["surviving_checkpoint_ok"] = bool(surviving_ok)
            if not surviving_ok:
                res["stderr"] = p.stderr[-2000:]
                print(json.dumps(res), flush=True)
                ok = False
                continue

            # elastic resume: N-device checkpoint -> universal -> M devices
            uni = ds_to_universal(save_dir, os.path.join(td, f"{name}_uni"),
                                  tag=tag)
            rb_out = os.path.join(td, f"{name}_b.json")
            rc_out = os.path.join(td, f"{name}_c.json")
            pb = _spawn_preempt_worker(M, total_steps,
                                       os.path.join(td, f"{name}_b_ckpt"),
                                       rb_out, resume_universal=uni)
            pc = _spawn_preempt_worker(M, total_steps,
                                       os.path.join(td, f"{name}_c_ckpt"),
                                       rc_out, load_dir=save_dir,
                                       resume_tag=tag)
            if pb.returncode != 0 or pc.returncode != 0:
                res["error"] = "resume run failed"
                res["stderr"] = (pb.stderr + pc.stderr)[-2000:]
                print(json.dumps(res), flush=True)
                ok = False
                continue
            b, c = _read_report(rb_out), _read_report(rc_out)
            res["resumed_start_step"] = b["start_step"]
            res["resumed_world"] = b["world"]
            # the gates
            res["global_batch_invariant"] = (
                b["global_batch"] == ref["global_batch"]
                and b["world"] == M and ref["world"] == N)
            res["resumed_from_surviving_step"] = b["start_step"] == k \
                and c["start_step"] == k
            res["losses_byte_identical"] = b["losses"] == c["losses"] \
                and len(b["losses"]) == total_steps - k
            res["compiles_after_resume_warmup"] = (
                b["compiles_after_warmup"] + c["compiles_after_warmup"])
            ref_tail = [ref["losses"][s] for s in sorted(b["losses"], key=int)]
            got_tail = [b["losses"][s] for s in sorted(b["losses"], key=int)]
            # across device counts reduction order differs in the last bits;
            # byte-equality holds at fixed world (above), continuity here
            res["loss_continuity_vs_original_world"] = bool(
                np.allclose(got_tail, ref_tail, rtol=5e-4, atol=1e-6))
            leg_ok = (res["killed_with_injection_exit"]
                      and res["global_batch_invariant"]
                      and res["resumed_from_surviving_step"]
                      and res["losses_byte_identical"]
                      and res["compiles_after_resume_warmup"] == 0
                      and res["loss_continuity_vs_original_world"])
            res["ok"] = bool(leg_ok)
            print(json.dumps(res), flush=True)
            ok = ok and leg_ok
    return ok


def snapshot(engine):
    import jax
    return (jax.device_get(engine.state), engine.global_steps,
            engine.global_samples, engine.micro_steps)


def restore(engine, snap):
    import jax
    state, steps, samples, micro = snap
    engine.state = jax.device_put(state, engine._state_shardings)
    engine.global_steps = steps
    engine.global_samples = samples
    engine.micro_steps = micro
    engine._pending_metrics.clear()
    engine._last_metrics = {}


def fresh_iter(engine, dataset, collate):
    """A deterministic loader — every run builds its own so all runs see the
    identical batch order (same seed, epoch 0)."""
    from deepspeed_tpu.runtime.dataloader import RepeatingLoader
    return RepeatingLoader(engine.deepspeed_io(dataset, collate_fn=collate,
                                               shuffle=True))


def sync_run(engine, dataset, collate, n: int):
    """Pre-PR loop: per step, item-by-item collate, inline staging, and an
    immediate blocking ``float(loss)`` — the per-step host sync the deferred
    drain removed."""
    it = iter(fresh_iter(engine, dataset, collate))
    losses = []
    gc.disable()
    t0 = time.time()
    for _ in range(n):
        losses.append(float(engine.train_batch(next(it))))
    wall = time.time() - t0
    gc.enable()
    return losses, wall


def pipe_run(engine, dataset, collate, n: int, prefetch: int):
    """The async loop: producer-thread staging + multi-step dispatch with the
    one-step-late metric drain; losses materialise once at the end."""
    from deepspeed_tpu.runtime.data_pipeline import PrefetchLoader
    pl = PrefetchLoader(fresh_iter(engine, dataset, collate),
                        prepare=engine._prepare_batch, prefetch=prefetch,
                        start_step=engine.global_steps)
    try:
        gc.disable()
        t0 = time.time()
        losses = engine.train_steps(n, data_iter=iter(pl))
        wall = time.time() - t0
        gc.enable()
    finally:
        pl.close()
    return [float(x) for x in losses], wall


def run_leg(builder, on_tpu: bool, steps: int, reps: int, prefetch: int):
    engine, dataset, collate, info = builder(on_tpu)
    snap = snapshot(engine)
    warm = max(2, min(4, steps))

    # warmup: compile the fused step + warm both orchestration paths, then
    # rewind the engine so every timed run starts from identical parameters
    sync_run(engine, dataset, collate, warm)
    restore(engine, snap)
    pipe_run(engine, dataset, collate, warm, prefetch)
    restore(engine, snap)

    c0 = engine.compiles
    speedups, sync_walls, pipe_walls = [], [], []
    equal = True
    first_losses = None
    acc = {"steps": 0, "wait": 0.0, "build": 0.0, "dispatch": 0.0,
           "drain": 0.0, "prefetched": 0}
    for _ in range(reps):
        losses_s, wall_s = sync_run(engine, dataset, collate, steps)
        restore(engine, snap)
        engine.train_stats.reset()   # phase breakdown: pipelined steps only
        losses_p, wall_p = pipe_run(engine, dataset, collate, steps, prefetch)
        st = engine.train_stats
        acc["steps"] += st.steps
        acc["wait"] += st.enqueue_wait_ms
        acc["build"] += st.host_build_ms
        acc["dispatch"] += st.dispatch_ms
        acc["drain"] += st.drain_ms
        acc["prefetched"] += st.prefetched_steps
        restore(engine, snap)
        equal = equal and losses_p == losses_s
        if first_losses is None:
            first_losses = losses_s
        # restored state + same loader seed => every rep must replay the
        # exact same stream; drift here means the restore (or staging) leaks
        equal = equal and losses_s == first_losses
        speedups.append(wall_s / wall_p)
        sync_walls.append(wall_s)
        pipe_walls.append(wall_p)
    n = max(1, acc["steps"])
    out = dict(info)
    med = int(np.argsort(speedups)[len(speedups) // 2])
    out.update({
        "steps": steps,
        "reps": reps,
        "prefetch": prefetch,
        "sync_steps_per_sec": round(steps / sync_walls[med], 2),
        "pipelined_steps_per_sec": round(steps / pipe_walls[med], 2),
        "speedup": round(float(np.median(speedups)), 2),
        "speedup_reps": [round(float(s), 2) for s in speedups],
        # the tentpole gate: identical math, different orchestration
        "losses_equal": bool(equal),
        "compiles_during_timed_runs": engine.compiles - c0,
        "enqueue_wait_ms_per_step": round(acc["wait"] / n, 3),
        "host_build_ms_per_step": round(acc["build"] / n, 3),
        "dispatch_ms_per_step": round(acc["dispatch"] / n, 3),
        "drain_ms_per_step": round(acc["drain"] / n, 3),
        "prefetched_fraction": round(acc["prefetched"] / n, 3),
    })
    engine.destroy()
    del engine
    gc.collect()   # drop this leg's device state before the next leg times
    return out


def run_trace_overhead_leg(on_tpu: bool, steps: int, reps: int, smoke: bool):
    """Tracer-overhead gate (ISSUE 7 / BENCH_r10): the SAME pipelined
    host-bound loop with span tracing OFF vs ON, reps interleaved so slow
    drift on this shared box hits both sides equally. Tracing ON must leave
    the loss stream byte-identical, add zero compiles, and cost <= 5% wall
    (the ring-record path: perf_counter pairs + one tuple store per span —
    export is NOT on the timed path). Smoke mode keeps the correctness gates
    but loosens the overhead bar (8 steps x 1 rep on 2 shared cores is
    noise, not signal)."""
    from deepspeed_tpu.monitor.trace import tracer
    engine, dataset, collate, info = build_host_bound_leg(on_tpu)
    snap = snapshot(engine)
    was_enabled = tracer.enabled   # $DSTPU_TRACE may have armed it
    warm = max(2, min(4, steps))
    tracer.enabled = False
    pipe_run(engine, dataset, collate, warm, prefetch=2)
    restore(engine, snap)
    tracer.configure(enabled=True)
    pipe_run(engine, dataset, collate, warm, prefetch=2)
    restore(engine, snap)

    c0 = engine.compiles
    off_walls, on_walls = [], []
    equal = True
    first = None
    for rep in range(reps):
        # alternate which side runs first: slow drift on this shared box
        # (allocator state, thread scheduling) hits both sides equally
        walls = {}
        for trace_on in ((False, True) if rep % 2 == 0 else (True, False)):
            tracer.enabled = bool(trace_on)
            losses, wall = pipe_run(engine, dataset, collate, steps, 2)
            restore(engine, snap)
            walls[trace_on] = wall
            if first is None:
                first = losses
            equal = equal and losses == first
        tracer.enabled = False
        off_walls.append(walls[False])
        on_walls.append(walls[True])
    # per-rep ratios, then the median: one GC'd or descheduled run perturbs
    # one ratio, not the whole estimate
    ratios = [on / off for on, off in zip(on_walls, off_walls)]
    overhead = float(np.median(ratios)) - 1.0
    spans = sum(c for c, _ in tracer.summary().values())
    tracer.enabled = was_enabled
    bar = 0.25 if smoke else 0.05
    out = dict(info)
    out.update({
        "leg": "trace_overhead",
        "steps": steps,
        "reps": reps,
        "traceoff_steps_per_sec": round(steps / float(np.median(off_walls)), 2),
        "traceon_steps_per_sec": round(steps / float(np.median(on_walls)), 2),
        "overhead_frac": round(overhead, 4),
        "overhead_frac_reps": [round(r - 1.0, 4) for r in ratios],
        "overhead_bar": bar,
        "spans_recorded": spans,
        "losses_equal": bool(equal),
        "compiles_during_timed_runs": engine.compiles - c0,
    })
    out["ok"] = bool(equal and out["compiles_during_timed_runs"] == 0
                     and overhead <= bar and spans > 0)
    engine.destroy()
    del engine
    gc.collect()
    return out


def run_zero3_overlap_leg(on_tpu: bool, steps: int, reps: int, smoke: bool):
    """ZeRO-3 collective-schedule leg (docs/TRAINING.md "ZeRO-3 collective
    schedule"): a param-heavy GPT2 stack sharded over an 8-way fsdp mesh,
    driven at stage3_prefetch_depth 0 (serial gather-then-compute baseline)
    vs 1 and 2 (pipelined prefetch + reduce-scatter under backward).

    Gates: per-step loss streams BYTE-IDENTICAL across all scheduled depths
    (the schedule moves collectives, never math); zero compiles during the
    timed runs; depth 0 shows zero span-measured overlap while depth >= 1
    shows structurally nonzero overlap (gather windows under other waves'
    residency windows, from the train/zero3 stamps). The implicit
    (XLA-scheduled) path is compared to fp32 tolerance only — its combiner
    reduces grads in a different order (~1 ulp drift).

    The steps/sec ratio is REPORTED against a 1.15x bar but only GATED on a
    real accelerator: a forced-host CPU mesh executes thunks serially, so
    scheduled overlap cannot convert to wall-clock there (the spans still
    prove the placement; same honesty pattern as the BENCH_r09 nvme leg)."""
    import jax
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHead
    from deepspeed_tpu.monitor import tracer
    from deepspeed_tpu.monitor.trace import install_from_env
    from deepspeed_tpu.runtime.zero import prefetch

    batch, seq = 8, 32
    n_embd, n_layer = (64, 4) if smoke else (192, 6)
    cfg_m = GPT2Config(vocab_size=LM_VOCAB, n_positions=seq,
                       n_embd=n_embd, n_layer=n_layer, n_head=4)
    rng = np.random.default_rng(0)
    batches = [{"input_ids": rng.integers(0, LM_VOCAB, size=(batch, seq))
                .astype(np.int32)} for _ in range(4)]

    # $DSTPU_TRACE must win the export dir BEFORE we force-enable: an
    # already-enabled tracer makes install_from_env a no-op
    install_from_env()
    was_enabled = tracer.enabled
    tracer.configure(enabled=True)   # arm the plan's trace taps at build

    def build(depth):
        model = GPT2LMHead(cfg_m)
        params = model.init(jax.random.PRNGKey(0), batches[0])["params"]
        z = {"stage": 3, "stage3_param_persistence_threshold": 0}
        if depth is not None:
            # bucket sized to roughly one transformer layer so the stack
            # packs into one wave per layer — multiple waves is what gives
            # the prefetch something to pipeline
            bucket = (1 << 18) if smoke else (1 << 21)
            z.update({"stage3_prefetch_depth": depth,
                      "allgather_bucket_size": bucket,
                      "reduce_bucket_size": bucket})
        engine, *_ = deepspeed_tpu.initialize(
            model=model, model_parameters=params,
            config={"train_batch_size": batch, "steps_per_print": 0,
                    "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                    "zero_optimization": z, "mesh": {"fsdp": 8}})
        return engine

    def run(engine, n, start):
        losses = []
        gc.disable()
        t0 = time.time()
        for i in range(n):
            losses.append(float(engine.train_batch(
                batches[(start + i) % len(batches)])))
        wall = time.time() - t0
        gc.enable()
        return losses, wall

    streams, rates, fracs, out = {}, {}, {}, {}
    compiles_during_timed = 0
    for depth in (0, 1, 2):
        prefetch.clear_stamps()
        engine = build(depth)
        assert engine._zero3_plan is not None, "zero3 schedule did not arm"
        losses, _ = run(engine, steps, start=0)        # includes compiles
        streams[depth] = [np.float32(x).tobytes() for x in losses]
        c0 = engine.compiles
        walls = []
        for r in range(reps):
            _, wall = run(engine, steps, start=(1 + r) * steps)
            walls.append(wall)
        engine.drain_metrics()
        compiles_during_timed += engine.compiles - c0
        rates[depth] = steps / float(np.median(walls))
        ev = dict((name, val) for name, val, _ in engine.zero3_stats.events(1))
        fracs[depth] = float(ev.get("train/zero3/overlap_frac", 0.0))
        if depth == 0:
            out["waves_per_step"] = engine._zero3_plan.n_waves
            out["gather_mb_per_step"] = round(
                engine._zero3_plan.gather_bytes_per_step / 1e6, 2)
        engine.destroy()
        del engine
        gc.collect()

    implicit = build(None)
    assert implicit._zero3_plan is None
    imp_losses, _ = run(implicit, steps, start=0)
    implicit.destroy()
    del implicit
    gc.collect()
    # keep tracing on when $DSTPU_TRACE armed an export dir (initialize()
    # arms it AFTER was_enabled was captured): the atexit exporter skips a
    # disabled tracer and bench_smoke's trace_check needs these lanes
    tracer.enabled = was_enabled or bool(tracer.trace_dir)

    base = [np.frombuffer(b, np.float32)[0] for b in streams[0]]
    byte_equal = streams[0] == streams[1] == streams[2]
    implicit_close = bool(np.allclose(imp_losses, base, rtol=1e-5))
    spans = sum(c for name, (c, _) in tracer.summary().items()
                if str(name).startswith("train/zero3"))
    speedup = rates[2] / rates[0] if rates[0] > 0 else 0.0
    bar = 1.15
    out.update({
        "leg": "zero3_overlap",
        "steps": steps, "reps": reps, "devices": len(jax.devices()),
        "model": {"n_embd": n_embd, "n_layer": n_layer, "seq": seq},
        "losses_equal": bool(byte_equal),
        "implicit_allclose": implicit_close,
        "compiles_during_timed_runs": compiles_during_timed,
        "steps_per_sec": {f"depth{d}": round(r, 3)
                          for d, r in rates.items()},
        "overlap_frac": {f"depth{d}": round(f, 4)
                         for d, f in fracs.items()},
        "zero3_spans_recorded": spans,
        "speedup_d2_vs_d0": round(speedup, 3),
        "speedup_bar": bar,
        "wall_clock_meaningful": bool(on_tpu),
    })
    if not on_tpu:
        out["caveat"] = (
            "forced-host CPU mesh: XLA:CPU executes thunks serially, so the "
            "scheduled overlap is visible in span placement (overlap_frac) "
            "but cannot convert to wall-clock; the 1.15x bar applies on "
            "hardware with async collectives")
    overlap_ok = fracs[0] == 0.0 and fracs[1] > 0.0 and fracs[2] > 0.0
    out["ok"] = bool(byte_equal and implicit_close
                     and compiles_during_timed == 0 and overlap_ok
                     and spans > 0
                     and (speedup >= bar or not on_tpu))
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--prefetch", type=int, default=2)
    # host_bound (the acceptance-gate leg) runs first so its numbers are not
    # skewed by allocator/thread-pool state the lm leg leaves behind
    ap.add_argument("--legs", default="host_bound,lm")
    ap.add_argument("--offload", action="store_true",
                    help="run the offloaded-optimizer legs "
                         "(offload_cpu,offload_nvme) instead of --legs")
    ap.add_argument("--preempt", action="store_true",
                    help="kill-and-resume leg (docs/ELASTICITY.md): kill a "
                         "subprocess run mid-step and mid-checkpoint-write, "
                         "resume on a different simulated device count, gate "
                         "byte-identical loss continuation")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny fast run for CI (scripts/bench_smoke.sh): "
                         "correctness gates only, throughput is noise")
    ap.add_argument("--trace-overhead", action="store_true",
                    help="span-tracer overhead leg (docs/OBSERVABILITY.md): "
                         "pipelined host-bound loop trace-off vs trace-on, "
                         "gating byte-identical losses, zero compiles, and "
                         "<=5%% overhead (BENCH_r10)")
    ap.add_argument("--zero3-overlap", action="store_true",
                    help="ZeRO-3 collective-schedule leg (docs/TRAINING.md): "
                         "prefetch depth 0 vs 1/2 over an 8-way fsdp mesh, "
                         "gating byte-identical loss streams, zero timed "
                         "compiles, and span-measured gather/compute overlap")
    # internal: one subprocess training run of the --preempt harness
    ap.add_argument("--preempt-worker", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--devices", type=int, default=0, help=argparse.SUPPRESS)
    ap.add_argument("--total-steps", type=int, default=12,
                    help=argparse.SUPPRESS)
    ap.add_argument("--save-dir", default="", help=argparse.SUPPRESS)
    ap.add_argument("--out", default="", help=argparse.SUPPRESS)
    ap.add_argument("--resume-universal", default="", help=argparse.SUPPRESS)
    ap.add_argument("--load-dir", default="", help=argparse.SUPPRESS)
    ap.add_argument("--resume-tag", default="", help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args.preempt_worker:
        preempt_worker(args)
        return
    if args.preempt:
        # 12 steps: cadence saves at 3/6/9/12, kill at 8 — small enough for
        # the CI smoke budget, large enough that every gate has teeth
        sys.exit(0 if run_preempt_leg(total_steps=12) else 1)
    if args.smoke:
        args.steps, args.reps = 8, 1
    if args.offload:
        args.legs = "offload_cpu,offload_nvme"
    if args.zero3_overlap:
        # the leg needs an 8-way fsdp mesh; on a CPU host force 8 virtual
        # devices BEFORE jax initialises (same discipline as tests/conftest)
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()

    import jax
    on_tpu = jax.default_backend() not in ("cpu",)
    from deepspeed_tpu.utils.compile_cache import setup_compile_cache
    setup_compile_cache(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if args.trace_overhead:
        # even in smoke mode the ratio needs a few interleaved reps — a
        # single 8-step pair on 2 shared cores measures the scheduler
        reps = max(3, args.reps) if args.smoke else max(5, args.reps)
        out = run_trace_overhead_leg(on_tpu, args.steps, reps, args.smoke)
        print(json.dumps(out), flush=True)
        sys.exit(0 if out["ok"] else 1)
    if args.zero3_overlap:
        out = run_zero3_overlap_leg(on_tpu, args.steps, args.reps, args.smoke)
        print(json.dumps(out), flush=True)
        sys.exit(0 if out["ok"] else 1)
    builders = {"lm": build_lm_leg, "host_bound": build_host_bound_leg}
    offload_legs = ("offload_cpu", "offload_nvme")
    bad = [l for l in args.legs.split(",")
           if l not in builders and l not in offload_legs]
    if bad:
        ap.error(f"unknown --legs entries {bad}; valid: "
                 f"{sorted(builders) + list(offload_legs)}")
    ok = True
    offload_outs = {}
    for leg in args.legs.split(","):
        if leg in offload_legs:
            if leg == "offload_nvme":
                import tempfile
                with tempfile.TemporaryDirectory() as nvme_dir:
                    out = run_offload_leg(on_tpu, args.steps, args.reps,
                                          args.smoke, nvme_dir=nvme_dir)
            else:
                out = run_offload_leg(on_tpu, args.steps, args.reps,
                                      args.smoke)
            offload_outs[leg] = out
        else:
            out = run_leg(builders[leg], on_tpu, args.steps, args.reps,
                          args.prefetch)
        print(json.dumps(out), flush=True)
        # gates: pipelined orchestration must not change the loss stream and
        # warm steady-state training must never compile — a staging or
        # bucket-cache regression shows up here before it becomes a
        # throughput mystery
        ok = ok and out["losses_equal"] \
            and out["compiles_during_timed_runs"] == 0
    if "offload_cpu" in offload_outs and "offload_nvme" in offload_outs:
        # the nvme tier's honest bound: no slower than the cpu tier by more
        # than the pure IO cost it actually paid (swap waits per step)
        cpu, nvme = offload_outs["offload_cpu"], offload_outs["offload_nvme"]
        cpu_step_ms = 1e3 / max(cpu["pipelined_steps_per_sec"], 1e-9)
        nvme_step_ms = 1e3 / max(nvme["pipelined_steps_per_sec"], 1e-9)
        io_ms = nvme["swap_ms_per_step"]
        # 1.5x slack on the measured IO: this box is 2 shared cores
        within = bool(
            nvme_step_ms <= cpu_step_ms + 1.5 * io_ms + 0.25 * cpu_step_ms)
        print(json.dumps({
            "leg": "offload_nvme_vs_cpu",
            "cpu_step_ms": round(cpu_step_ms, 3),
            "nvme_step_ms": round(nvme_step_ms, 3),
            "nvme_io_ms_per_step": round(io_ms, 3),
            "within_io_cost": within,
        }), flush=True)
        ok = ok and within
    if not ok:
        sys.exit(1)


if __name__ == "__main__":
    main()
