"""Colocated rollout bench (docs/TRAINING.md "Colocated rollout", BENCH_r19).

Three legs over ONE colocated train+serve pair (tiny GPT-2 on CPU for the
smoke; real sizes on accelerator hardware):

- ``sync``: the WeightBridge's device-resident reshard vs the universal
  checkpoint round-trip it replaces (save_checkpoint -> ds_to_universal ->
  load_universal -> host unflatten -> re-upload -> the SAME serving-layout
  program). Identical source, identical output layout, byte-equality
  gated — the measured delta is exactly the host/disk legs the bridge
  deletes. Full mode gates the >=5x speedup; smoke gates correctness only.
- ``swap``: >=3 consecutive train->sync->swap cycles into a WARMED engine,
  gating zero new compiles, byte-identical post-swap greedy streams vs a
  freshly built engine on the same weights, and the KV allocator back at
  baseline.
- ``interleave``: the full RolloutLoop (frontend generates rollouts that
  feed the next train batch) vs the naive rebuild-the-engine-per-update
  loop, byte-identical rollouts gated; full mode also gates the steps/s
  advantage.

Every leg prints one JSON line; non-smoke runs aggregate into
``BENCH_r19.json``. The bridge/loop stamps emit the ``train/rollout/*``
trace lanes scripts/trace_check.py requires in the bench smoke.
"""

import argparse
import json
import os
import statistics
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

VOCAB = 128


def _median(xs):
    return statistics.median(xs)


def build_pair(prefix_cache=True):
    import jax.numpy as jnp
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHead
    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2

    model = GPT2LMHead(GPT2Config.tiny(vocab_size=VOCAB))
    import jax
    params = model.init(jax.random.PRNGKey(0),
                        {"input_ids": np.zeros((2, 16), np.int32)})["params"]
    cfg = {"train_batch_size": 8, "gradient_accumulation_steps": 1,
           "steps_per_print": 0,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
           "zero_optimization": {"stage": 1}, "mesh": {}}
    engine, *_ = deepspeed_tpu.initialize(model=model,
                                          model_parameters=params, config=cfg)
    econf = {"dtype": jnp.float32,
             "state_manager": {"max_tracked_sequences": 8,
                               "max_ragged_sequence_count": 4,
                               "max_ragged_batch_size": 96,
                               "max_context": 176,
                               "prefill_chunk_size": 32},
             "kv_cache": {"block_size": 16, "num_blocks": 16},
             "serving": {"decode_slice": 4, "idle_wait_s": 0.005}}
    if prefix_cache:
        econf["prefix_cache"] = {"enabled": True}
    serve = InferenceEngineV2(model=model, model_parameters=params,
                              config=econf)
    return engine, serve, model, params


def _train_step(engine, seed):
    rng = np.random.default_rng(seed)
    engine.train_batch({"input_ids":
                        rng.integers(0, VOCAB, (8, 16)).astype(np.int32)})


def _leaves_bytes_equal(a, b):
    import jax
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.asarray(x).tobytes() == np.asarray(y).tobytes()
        for x, y in zip(la, lb))


def run_sync_leg(smoke, reps):
    """Bridge sync vs the universal-checkpoint round-trip, same program on
    both sides — the measured delta is the host/disk legs."""
    import jax
    from deepspeed_tpu.checkpoint import ds_to_universal, load_universal
    from deepspeed_tpu.checkpoint.state import unflatten_into
    from deepspeed_tpu.inference.v2.ragged_model import adapt_model
    from deepspeed_tpu.utils.tree import tree_cast

    engine, serve, model, params = build_pair(prefix_cache=False)
    _train_step(engine, 1)
    bridge = serve.weight_bridge(engine, donate=False)
    bridge.sync()                                    # build (untimed, once)

    dtype = serve.config.dtype
    max_ctx = serve.config.state_manager.max_context
    to_serve = jax.jit(
        lambda p: adapt_model(serve.family, tree_cast(p, dtype),
                              serve.model_config, max_context=max_ctx)[1],
        out_shardings=jax.tree_util.tree_map(lambda a: a.sharding,
                                             serve.weights))

    sync_s, disk_s = [], []
    equal = True
    with tempfile.TemporaryDirectory() as tmp:
        # warm the baseline program too: neither side pays compiles in the
        # timed region
        host0 = jax.tree_util.tree_map(np.asarray,
                                       engine.rollout_source_params())
        jax.block_until_ready(to_serve(jax.device_put(host0)))
        for r in range(reps):
            _train_step(engine, 10 + r)
            t0 = time.perf_counter()
            w_sync = bridge.sync()
            t1 = time.perf_counter()
            sync_s.append(t1 - t0)

            ck = os.path.join(tmp, f"ck{r}")
            uni = os.path.join(tmp, f"uni{r}")
            t0 = time.perf_counter()
            engine.save_checkpoint(ck, tag="b")
            ds_to_universal(ck, uni, tag="b")
            master, _, _ = load_universal(uni)
            host = unflatten_into(
                jax.tree_util.tree_map(np.asarray, params), master)
            w_disk = to_serve(jax.device_put(host))
            jax.block_until_ready(w_disk)
            t1 = time.perf_counter()
            disk_s.append(t1 - t0)
            equal = equal and _leaves_bytes_equal(w_sync, w_disk)

    speedup = _median(disk_s) / max(_median(sync_s), 1e-9)
    out = {"leg": "sync", "reps": reps, "bytes": bridge.nbytes,
           "sync_ms_median": 1e3 * _median(sync_s),
           "universal_roundtrip_ms_median": 1e3 * _median(disk_s),
           "speedup": speedup, "weights_byte_equal": equal,
           "bridge_compiles": bridge.compiles, "smoke": smoke}
    # smoke: byte-equality only (2-core CI wall times are noise); the >=5x
    # bar is the full-size gate (BENCH_r19)
    out["ok"] = equal and (smoke or speedup >= 5.0)
    return out


def run_swap_leg(smoke, n_swaps=3):
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2

    engine, serve, model, params = build_pair(prefix_cache=False)
    bridge = serve.weight_bridge(engine)
    prompt = list(range(1, 12))
    serve.generate([prompt], max_new_tokens=8)       # warm the ladders
    kv_free0 = serve.allocator.free_blocks
    c0 = serve.compiles

    for i in range(n_swaps):
        _train_step(engine, 20 + i)
        serve.swap_weights(bridge.sync())
    out_tokens = serve.generate([prompt], max_new_tokens=8)
    compiles = serve.compiles - c0

    fresh = InferenceEngineV2(
        model=model,
        model_parameters=jax.tree_util.tree_map(
            np.asarray, engine.rollout_source_params()),
        config={"dtype": jnp.float32,
                "state_manager": {"max_tracked_sequences": 8,
                                  "max_ragged_sequence_count": 4,
                                  "max_ragged_batch_size": 96,
                                  "max_context": 176,
                                  "prefill_chunk_size": 32},
                "kv_cache": {"block_size": 16, "num_blocks": 16}})
    ref_tokens = fresh.generate([prompt], max_new_tokens=8)

    out = {"leg": "swap", "swaps": n_swaps,
           "weight_version": serve.weight_version,
           "compiles_after_warmup": compiles,
           "streams_equal": out_tokens == ref_tokens,
           "weights_byte_equal": _leaves_bytes_equal(serve.weights,
                                                     fresh.weights),
           "kv_allocator_at_baseline":
               serve.allocator.free_blocks == kv_free0,
           "smoke": smoke}
    out["ok"] = (compiles == 0 and out["streams_equal"]
                 and out["weights_byte_equal"]
                 and out["kv_allocator_at_baseline"])
    return out


def run_interleave_leg(smoke, rounds):
    """RolloutLoop vs rebuild-the-serving-engine-per-update, identical
    seeded prompts; the naive loop re-pays engine construction + compile
    ladders every policy update."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.runtime.colocated import RolloutLoop

    n_prompts, gen = 3, 4

    def prompts_for(rnd):
        r = np.random.default_rng(1000 + rnd)
        return [r.integers(1, VOCAB, size=8).tolist()
                for _ in range(n_prompts)]

    def collate(rollouts):
        rows = [(p + t + [0] * 16)[:16] for p, t in rollouts]
        return {"input_ids":
                np.asarray(rows, np.int32).repeat(3, axis=0)[:8]}

    # --- colocated -------------------------------------------------------
    engine, serve, model, params = build_pair()
    fe = serve.serving_frontend()
    # run() numbers rounds from 0 on every call; key the seeded prompts by
    # a global update counter instead so the warm round consumes update 0
    # and the timed rounds line up with the naive loop's updates 1..N
    update = {"n": 0}

    def prompts_for_loop(_rnd):
        n = update["n"]
        update["n"] += 1
        return prompts_for(n)

    loop = RolloutLoop(engine, fe, prompt_fn=prompts_for_loop,
                       collate_fn=collate, steps_per_round=1,
                       max_new_tokens=gen, request_timeout=120.0)
    co_rollouts = {}
    orig_gen = loop._generate

    def _capture(rnd):
        n = update["n"]
        out = orig_gen(rnd)
        co_rollouts[n] = [t for _, t in out]
        return out
    loop._generate = _capture
    loop.run(1, align=True)                          # warm every ladder
    t0 = time.perf_counter()
    loop.run(rounds, align=False)
    co_s = time.perf_counter() - t0
    stats = loop.stats
    loop.close()
    fe.close()

    # --- naive: rebuild the serving engine every update ------------------
    engine2, serve2, model2, _ = build_pair()
    econf = {"dtype": jnp.float32,
             "state_manager": {"max_tracked_sequences": 8,
                               "max_ragged_sequence_count": 4,
                               "max_ragged_batch_size": 96,
                               "max_context": 176,
                               "prefill_chunk_size": 32},
             "kv_cache": {"block_size": 16, "num_blocks": 16}}

    def naive_round(rnd):
        host = jax.tree_util.tree_map(np.asarray,
                                      engine2.rollout_source_params())
        eng = InferenceEngineV2(model=model2, model_parameters=host,
                                config=econf)
        prompts = prompts_for(rnd)
        full = eng.generate(prompts, max_new_tokens=gen)
        # generate() returns prompt+continuation; the frontend streams only
        # the continuation — train on the same rows the colocated loop does
        outs = [f[len(p):] for p, f in zip(prompts, full)]
        engine2.train_batch(collate(list(zip(prompts, outs))))
        return outs

    naive_round(0)                                   # align + warm parity
    na_rollouts = {}
    t0 = time.perf_counter()
    for rnd in range(1, rounds + 1):
        na_rollouts[rnd] = naive_round(rnd)
    na_s = time.perf_counter() - t0

    # both loops saw the same seeded prompts at the same policy version,
    # so the greedy rollouts must agree byte-for-byte
    rollouts_equal = all(co_rollouts.get(r) == na_rollouts.get(r)
                         for r in range(1, rounds + 1))
    speedup = na_s / max(co_s, 1e-9)
    out = {"leg": "interleave", "rounds": rounds,
           "colocated_s": co_s, "naive_rebuild_s": na_s,
           "rounds_per_s_colocated": rounds / max(co_s, 1e-9),
           "rounds_per_s_naive": rounds / max(na_s, 1e-9),
           "speedup": speedup, "rollouts_byte_equal": rollouts_equal,
           "sync_ms_per_round": stats.sync_ms / max(1, stats.rounds),
           "swap_ms_per_round": stats.swap_ms / max(1, stats.rounds),
           "generate_ms_per_round":
               stats.generate_ms / max(1, stats.rounds),
           "smoke": smoke}
    out["ok"] = rollouts_equal and (smoke or speedup >= 1.0)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="correctness gates only, tiny sizes (CI)")
    ap.add_argument("--reps", type=int, default=0)
    ap.add_argument("--rounds", type=int, default=0)
    ap.add_argument("--out", default="BENCH_r19.json")
    args = ap.parse_args()
    reps = args.reps or (2 if args.smoke else 5)
    rounds = args.rounds or (2 if args.smoke else 4)

    from deepspeed_tpu.utils.compile_cache import setup_compile_cache
    setup_compile_cache(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    ok = True
    results = {}
    for name, fn in (("sync", lambda: run_sync_leg(args.smoke, reps)),
                     ("swap", lambda: run_swap_leg(args.smoke)),
                     ("interleave",
                      lambda: run_interleave_leg(args.smoke, rounds))):
        out = fn()
        results[name] = out
        print(json.dumps(out), flush=True)
        ok = ok and out["ok"]
    if not args.smoke:
        with open(args.out, "w") as f:
            json.dump(results, f, indent=2)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
