"""Inference (FastGen-class) throughput benchmark on the local chip(s).

Parity role: the reference's ``benchmarks/README.md`` defers its inference
suite to DeepSpeedExamples; this in-repo script measures the v2
continuous-batching engine directly so the FastGen-style numbers are
reproducible here:

  * decode tokens/sec at a given concurrency (all-decode steady state)
  * prefill+decode mixed throughput (Dynamic SplitFuse schedule)

Usage: ``python benchmarks/inference_bench.py [--layers N] [--hidden H]
[--seqs S] [--prompt P] [--gen G]``.  Defaults size a ~0.5B llama-style model
that fits a single v5e chip in bf16.  Prints one JSON line per phase.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--hidden", type=int, default=1536)
    ap.add_argument("--heads", type=int, default=12)
    ap.add_argument("--kv-heads", type=int, default=12)
    ap.add_argument("--vocab", type=int, default=32000)
    ap.add_argument("--seqs", type=int, default=32, help="concurrent sequences")
    ap.add_argument("--prompt", type=int, default=256)
    ap.add_argument("--gen", type=int, default=64)
    args = ap.parse_args()

    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                      intermediate_size=args.hidden * 4,
                      num_hidden_layers=args.layers,
                      num_attention_heads=args.heads,
                      num_key_value_heads=args.kv_heads,
                      max_position_embeddings=args.prompt + args.gen + 64,
                      dtype=jnp.bfloat16)
    model = LlamaForCausalLM(cfg)
    rng = np.random.RandomState(0)
    sample = jnp.asarray(rng.randint(0, args.vocab, size=(1, 8)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0),
                        {"input_ids": sample})["params"]
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))

    engine = InferenceEngineV2(
        model=model, model_parameters=params,
        config={"state_manager": {
            "max_tracked_sequences": args.seqs,
            "max_ragged_batch_size": max(args.seqs * 2, args.prompt * 2),
            "max_context": args.prompt + args.gen + 64,
        }})

    prompts = [rng.randint(0, args.vocab, size=(args.prompt,)).astype(np.int32)
               for _ in range(args.seqs)]

    # -- prefill ----------------------------------------------------------- #
    # run once cold (compiles chunk shapes), flush, then measure warm
    uids = list(range(args.seqs))
    logits = engine.put(uids, prompts)
    assert logits.shape[0] == args.seqs
    engine.flush(uids)
    t0 = time.time()
    logits = engine.put(uids, prompts)
    dt_prefill = time.time() - t0
    prefill_tput = args.seqs * args.prompt / dt_prefill

    # -- decode steady state (fused multi-step device loop) ----------------- #
    # decode_steps fuses CHUNK decode iterations (sample -> forward -> sample)
    # into one XLA program, so the host syncs once per CHUNK tokens.  Warm
    # thoroughly first: the remote runtime's first ~50 executions pay one-off
    # costs that would otherwise pollute the window.
    CHUNK = 32
    for _ in range(3):
        engine.decode_steps(uids, CHUNK)
    t0 = time.time()
    steps = 0
    while steps < args.gen:
        out = engine.decode_steps(uids, CHUNK)
        steps += CHUNK
    dt_decode = time.time() - t0
    decode_tput = args.seqs * steps / dt_decode
    engine.flush(uids)

    dev = getattr(jax.devices()[0], "device_kind", "?")
    print(json.dumps({
        "metric": "inference_v2_decode_tokens_per_sec",
        "value": round(decode_tput, 1), "unit": "tokens/s",
        "extra": {"prefill_tokens_per_sec": round(prefill_tput, 1),
                  "n_params": int(n_params), "seqs": args.seqs,
                  "prompt": args.prompt, "gen": args.gen,
                  "backend": jax.default_backend(), "device": dev}}))


if __name__ == "__main__":
    main()
