"""Continuous-batching serving benchmark (FastGen system-level analog).

Parity role: the reference's FastGen throughput-latency evaluation
(``blogs/deepspeed-fastgen/README.md`` §B — sweep client load, measure
effective tokens/sec and per-token latency under CONTINUOUS batching, where
prompt prefills are admitted while other sequences decode). The unit benches
in ``bench.py`` measure prefill and decode in isolation; this harness drives
the engine the way a serving frontend does:

  a steady arrival stream of prompts -> admit when can_schedule() ->
  one scheduler pass per iteration (mixed chunk+decode batches) ->
  sample on device -> retire sequences at their generation budget.

Prints one JSON line per load point:
  {"arrival_rate": r, "gen_tokens_per_sec": ..., "total_tokens_per_sec": ...,
   "mean_tbt_ms": ..., "p95_tbt_ms": ..., "mixed_pass_fraction": ...}

Usage:
  python benchmarks/serving_bench.py [--seqs 32] [--prompt 128] [--gen 64]
                                     [--rates 2,6] [--duration 20]

On CPU (tests/CI) the model is tiny; on TPU the 0.55B bench config is used.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

# runnable as `python benchmarks/serving_bench.py` from a bare checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_engine(on_tpu: bool, seqs: int, prompt: int, gen: int,
                 burst: int = 8, int8: bool = False,
                 prefix_cache: bool = False, warmup: bool = False,
                 warmup_bursts: bool = True, spec_k: int = 0,
                 ctx_slack: int = 0, extra_config=None):
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    if on_tpu:
        layers, hidden, heads, vocab = 12, 1536, 12, 32000
    else:
        layers, hidden, heads, vocab = 2, 64, 4, 256
    # slack covers the waste margin (4*burst) + one burst overshoot
    ctx = prompt + gen + 6 * burst + ctx_slack
    cfg = LlamaConfig(vocab_size=vocab, hidden_size=hidden,
                      intermediate_size=hidden * 4, num_hidden_layers=layers,
                      num_attention_heads=heads, num_key_value_heads=heads,
                      max_position_embeddings=ctx,
                      dtype=jnp.bfloat16 if on_tpu else jnp.float32)
    model = LlamaForCausalLM(cfg)
    import contextlib

    @contextlib.contextmanager
    def no_pallas():  # init's forward values never affect the params
        old = os.environ.get("DSTPU_DISABLE_PALLAS")
        os.environ["DSTPU_DISABLE_PALLAS"] = "1"
        try:
            yield
        finally:
            if old is None:
                os.environ.pop("DSTPU_DISABLE_PALLAS", None)
            else:
                os.environ["DSTPU_DISABLE_PALLAS"] = old

    with no_pallas():
        params = jax.jit(model.init)(
            jax.random.PRNGKey(0),
            {"input_ids": jnp.zeros((1, 8), jnp.int32)})["params"]
    econf = {"state_manager": {
        "max_tracked_sequences": seqs,
        "max_ragged_sequence_count": seqs,
        # chunk capacity for a handful of concurrent prefills per pass
        "max_ragged_batch_size": 4 * prompt + seqs,
        "prefill_chunk_size": prompt,
        "max_context": ctx}}
    if int8:
        # weight-only int8 serving (the v2 mixed-GEMM analog): decode is
        # weight-read bound, int8 halves the stream (bench.py mha32 legs)
        econf["quantization"] = {"weight_bits": 8}
    if prefix_cache:
        econf["prefix_cache"] = {"enabled": True}
    if spec_k:
        # speculative decoding (inference/v2/spec/): warmup() then covers
        # the (bucket, k) verify grid beside the plain decode grid
        econf["spec_decode"] = {"enabled": True, "k": spec_k}
    if warmup:
        # AOT-warm the whole decode bucket grid (and, for legs that run
        # fused bursts, the burst length) so the timed legs never observe an
        # XLA compile; the multistep scan programs are the slowest compiles
        # in the set, so legs that never burst skip them
        econf["compile"] = {"warmup": True,
                            "warmup_decode_steps": [burst] if warmup_bursts
                            else []}
    if extra_config:
        econf.update(extra_config)
    engine = InferenceEngineV2(model=model, model_parameters=params,
                               config=econf)
    return engine, vocab


def run_load_point(engine, vocab: int, rate: float, seqs: int, prompt: int,
                   gen: int, duration: float, rng: np.random.RandomState,
                   burst: int = 8, mode: str = "burst"):
    """Drive the serving loop at ``rate`` prompt arrivals/sec for ``duration``
    seconds.

    Policy (iteration-level scheduling, RTT-amortised): owed arrivals are
    admitted and prefilled through mixed scheduler passes; between admissions
    ALL live sequences advance through fused ``decode_steps`` bursts (one
    host<->device round trip per ``burst`` tokens — through a remote runtime
    the per-token RTT otherwise dominates; measured ~250 ms/iteration on the
    tunnel vs ~6 ms of decode compute). The decode set is kept at a FIXED
    size once saturated: retired sequences are replaced by owed arrivals in
    the same iteration, so the fused-decode program never recompiles; when no
    arrival is owed, a retired slot generates into waste until one is (the
    waste is reported).

    ``mode="mixed"`` (VERDICT r4 weak #3 — the burst leg never exercised
    SplitFuse COMPOSITION): every iteration advances all live sequences by
    ONE token THROUGH SCHEDULER PASSES — their decode rows share each pass
    with any newly admitted prompts' chunks, the chunk+decode composition
    the FastGen scheduler was built for (reference blogs/deepspeed-fastgen
    §B Dynamic SplitFuse) — so ``mixed_pass_fraction`` measures real
    composed passes. Costs one host round trip per token (no fused burst):
    through the tunnel its TOTAL throughput is RTT-bound, so the artifact
    reports both legs side by side.
    """
    next_uid = 10_000
    arrivals = 0
    active = {}           # uid -> generated-token count (may exceed goal: waste)
    # per-sequence generation target. In 'mixed' mode targets STAGGER
    # (uniform in [gen/2, 3*gen/2]) so retirements — and therefore
    # admissions — spread across iterations instead of the whole set
    # retiring in lockstep; a rotation then composes its prompt chunks with
    # the other sequences' decode rows in the same pass, which is the
    # SplitFuse mixing this leg measures. 'burst' keeps a fixed gen for
    # round-over-round comparability.
    goal = {}
    dummies = set()       # slot-keeping sequences; all their tokens are waste
    tbts = []
    gen_tokens = 0
    wasted_tokens = 0
    prompt_tokens = 0
    passes = mixed_passes = 0
    decode_bursts = 0
    # a retired slot may generate at most this much waste before it is rotated
    # onto a fresh (dummy) sequence — bounds KV growth under the ctx budget
    waste_margin = 4 * burst

    def admit(n, dummy=False):
        nonlocal next_uid, arrivals, prompt_tokens
        admitted = 0
        for _ in range(n):
            if len(active) >= seqs:
                break
            uid, next_uid = next_uid, next_uid + 1
            if not engine.can_schedule([uid], [prompt]):
                break
            toks = rng.randint(0, vocab, size=(prompt,)).astype(np.int32)
            engine.scheduler.add_tokens(uid, toks)
            active[uid] = 0
            goal[uid] = (int(rng.randint(max(1, gen // 2),
                                         gen + gen // 2 + 1))
                         if mode == "mixed" else gen)
            if dummy:
                dummies.add(uid)
            else:
                arrivals += 1
                prompt_tokens += prompt
            admitted += 1
        return admitted

    def run_passes():
        """Drain pending prompt chunks through engine passes (mixed when
        decode feeds coexist), counting pass composition."""
        nonlocal passes, mixed_passes
        while engine.scheduler.has_pending():
            orig = engine.scheduler.schedule_pass
            seen = {}

            def counting():
                b = orig()
                if b is not None:
                    seen["mixed"] = bool(b.chunk_uids and b.decode_uids)
                return b

            engine.scheduler.schedule_pass = counting
            try:
                engine._run_pass()
            finally:
                engine.scheduler.schedule_pass = orig
            if seen:
                passes += 1
                mixed_passes += int(seen.get("mixed", False))

    admit(seqs)           # fill to the cap; rate governs REPLACEMENTS
    run_passes()
    t0 = time.time()
    while time.time() - t0 < duration:
        owed = int((time.time() - t0) * rate) - arrivals + seqs
        retired = [u for u, g in active.items() if g >= goal[u]]
        # rotate retired slots: onto real arrivals when owed, else onto dummy
        # slot-keepers once they exceed the waste margin (bounds ctx usage)
        rotate = (retired[:max(owed, 0)] +
                  [u for u in retired[max(owed, 0):]
                   if active[u] >= goal[u] + waste_margin])
        if rotate:
            for u in rotate:
                engine.flush([u])
                dummies.discard(u)
                del active[u]
                del goal[u]
            n_real = admit(min(max(owed, 0), len(rotate)))
            admit(len(rotate) - n_real, dummy=True)
            if mode != "mixed":
                run_passes()   # prefill the replacements

        uids = list(active)
        if not uids:
            time.sleep(0.001)
            continue
        if mode == "mixed":
            # one token per sequence through COMPOSED scheduler passes: the
            # decode rows ride the same pass as any pending prompt chunks
            # (including this iteration's admissions, deliberately left
            # undrained above)
            ready = [u for u in uids
                     if len(engine.scheduler.seqs[u].pending) == 0]
            if not ready:
                run_passes()
                continue
            tb0 = time.time()
            nxt = engine.sample_next(ready)
            # add_tokens directly (NOT _put_nofetch, which drains passes
            # internally and would bypass the composition counter)
            for u, t in zip(ready, nxt):
                engine.scheduler.add_tokens(u, np.asarray([t], np.int32))
            run_passes()
            tb = time.time() - tb0
            step = 1
        else:
            tb0 = time.time()
            engine.decode_steps(uids, burst)
            tb = time.time() - tb0
            decode_bursts += 1
            step = burst
            ready = uids
        for u in ready:
            waste = u in dummies or active[u] >= goal[u]
            active[u] += step
            if waste:
                wasted_tokens += step
            else:
                counted = min(step, goal[u] - (active[u] - step))
                gen_tokens += counted
                wasted_tokens += step - counted   # gen-boundary overshoot
                tbts.extend([tb / step] * counted)

    dt = time.time() - t0
    for u in list(active):
        engine.flush([u])
    total = gen_tokens + prompt_tokens
    return {
        "mode": mode,
        "arrival_rate": rate,
        "concurrency_cap": seqs,
        "gen_tokens_per_sec": round(gen_tokens / dt, 1),
        "total_tokens_per_sec": round(total / dt, 1),
        "mean_tbt_ms": round(1e3 * float(np.mean(tbts)), 2) if tbts else None,
        "p95_tbt_ms": (round(1e3 * float(np.percentile(tbts, 95)), 2)
                       if tbts else None),
        "completed": arrivals - len(active),
        "passes": passes,
        "mixed_pass_fraction": round(mixed_passes / passes, 3) if passes else 0,
        "decode_bursts": decode_bursts,
        "wasted_token_fraction": round(wasted_tokens / max(1, gen_tokens +
                                                           wasted_tokens), 3),
    }


def run_shared_prefix(on_tpu: bool, n_requests: int, prefix_len: int,
                      tail_len: int, gen: int, seed: int = 0):
    """Shared-prefix workload (prefix-cache leg): ``n_requests`` prompts share
    one long system prompt and differ only in a short tail — the traffic shape
    automatic prefix caching (SGLang RadixAttention / vLLM APC) targets.
    Requests are served sequentially on a cache-on and a cache-off engine
    (identical weights; params are seeded deterministically) and the leg
    reports computed prefill tokens, cache hit rate, and — the correctness
    gate — whether greedy outputs are EXACTLY equal between the two.

    Both engines run with the packed-prefill fast path disabled (every pass
    through the paged forward): a cache hit turns a from-zero prefill into a
    continuation, which ALWAYS takes the paged path, while a cache-off engine
    takes the packed path — and the two attention implementations carry a
    benign per-path numerical variance (~3e-2 on this random-init bench model
    at 288 tokens, measured against the dense v1 engine: both paths sit the
    same distance from dense). Holding the kernel path constant makes the
    equality gate test exactly what the cache changes: which KV pages back
    the computation."""
    prompt_len = prefix_len + tail_len

    def serve(prefix_cache: bool):
        engine, vocab = build_engine(on_tpu, seqs=4, prompt=prompt_len,
                                     gen=gen, prefix_cache=prefix_cache)
        orig = engine.scheduler.schedule_pass

        def no_fast_path():
            b = orig()
            if b is not None:
                b.pure_prefill = False
            return b

        engine.scheduler.schedule_pass = no_fast_path
        rng = np.random.RandomState(seed)
        prefix = rng.randint(0, vocab, size=(prefix_len,)).astype(np.int32)
        outs = []
        t0 = time.time()
        try:
            for i in range(n_requests):
                tail = rng.randint(0, vocab, size=(tail_len,)).astype(np.int32)
                prompt = np.concatenate([prefix, tail])
                uid = 5000 + i
                engine._put_nofetch([uid], [prompt])
                toks = []
                for j in range(gen):
                    t = int(engine.sample_next([uid])[0])  # greedy, on device
                    toks.append(t)
                    if j < gen - 1:
                        engine._put_nofetch([uid], [np.asarray([t], np.int32)])
                engine.flush([uid])
                outs.append(toks)
        finally:
            # drop the instance attr (lookup falls back to the class method):
            # the wrapper's closure holds a bound method of the scheduler — a
            # reference cycle that would keep this engine's device KV pool
            # alive past `del eng_off` until a gc pass
            del engine.scheduler.schedule_pass
        wall = time.time() - t0
        return engine, outs, wall

    eng_off, outs_off, wall_off = serve(False)
    # pull the counter and DROP the cache-off engine before building the
    # cache-on one: two engines (weights + full KV pool each) alive at once
    # would double device memory for the whole second leg
    off_prefill = eng_off.scheduler.prefill_tokens_completed
    del eng_off
    eng_on, outs_on, wall_on = serve(True)
    on_prefill = eng_on.scheduler.prefill_tokens_completed
    st = eng_on.prefix_cache.stats
    return {
        "leg": "shared_prefix",
        "requests": n_requests,
        "prefix_tokens": prefix_len,
        "tail_tokens": tail_len,
        "gen": gen,
        "prefill_tokens_cache_off": off_prefill,
        "prefill_tokens_cache_on": on_prefill,
        "prefill_reduction": round(1.0 - on_prefill / max(1, off_prefill), 3),
        "cache_hit_rate": round(st.hit_rate, 3),
        "tokens_saved": st.tokens_saved,
        "evictions": st.evictions,
        "cow_copies": st.cow_copies,
        "outputs_equal": outs_on == outs_off,
        "wall_s_cache_off": round(wall_off, 2),
        "wall_s_cache_on": round(wall_on, 2),
    }


def run_steady_state(on_tpu: bool, seqs: int, prompt: int, gen: int,
                     seed: int = 0):
    """Steady-state decode leg: the same fixed decode set generates ``gen``
    tokens through (a) the per-token serving loop the engine shipped with
    before the pipeline — blocking on-device-sample fetch + full scheduler
    pass per token — and (b) the async double-buffered ``DecodePipeline``
    (fused on-device sampling, bucketed descriptors, one-step-late drain).

    The correctness gate: greedy token streams must be BYTE-IDENTICAL
    between the two loops (same forward math, different orchestration), and
    the pipeline's per-step host transfer must be exactly one int32 row per
    bucket slot (the monitor's fetch-bytes field). Reported: tokens/sec per
    loop, the speedup, p50/p99 per-token latency, and the pipeline's
    per-step phase breakdown. Both loops run a short untimed round first so
    the timed rounds are compile-free (asserted via the engine's compile
    counter).
    """
    from deepspeed_tpu.utils.caching import next_pow2
    # no fused bursts in this leg: warm only the passes + the step-prog grid
    engine, vocab = build_engine(on_tpu, seqs=seqs, prompt=prompt, gen=gen,
                                 warmup=True, warmup_bursts=False)
    rng = np.random.RandomState(seed)
    prompts = [rng.randint(0, vocab, size=(prompt,)).astype(np.int32)
               for _ in range(seqs)]
    uid_base = [20_000]

    def prefill():
        uid_base[0] += seqs
        uids = list(range(uid_base[0], uid_base[0] + seqs))
        engine._put_nofetch(uids, prompts)
        return uids

    def sync_leg(n):
        """Pre-PR loop: per token, one blocking token-row fetch, scheduler
        bookkeeping, a full ragged-pass descriptor build, one pass."""
        uids = prefill()
        outs = [[] for _ in uids]
        lat = []
        t0 = time.time()
        for j in range(n):
            tb = time.time()
            toks = engine.sample_next(uids)   # blocks: sample + row fetch
            for i, t in enumerate(toks):
                outs[i].append(int(t))
            if j < n - 1:                     # last token's pass is unread
                engine._put_nofetch(uids, [np.asarray([t], np.int32)
                                           for t in toks])
            lat.append(time.time() - tb)
        wall = time.time() - t0
        engine.flush(uids)
        return outs, wall, [1e3 * x for x in lat]

    def pipe_leg(n):
        uids = prefill()
        pipe = engine.decode_pipeline(uids)
        st = engine.pipeline_stats
        st.reset()
        t0 = time.time()
        out = pipe.run(n)                     # fully drained on return
        wall = time.time() - t0
        engine.flush(uids)
        return [list(map(int, row)) for row in out], wall, list(st.step_wall_ms)

    # untimed rounds: compile/warm everything either loop touches
    sync_leg(min(4, gen))
    pipe_leg(min(4, gen))
    c0 = engine.compiles
    outs_sync, wall_sync, lat_sync = sync_leg(gen)
    outs_pipe, wall_pipe, lat_pipe = pipe_leg(gen)
    compiles = engine.compiles - c0
    st = engine.pipeline_stats
    bucket = next_pow2(seqs)
    tok = seqs * gen
    n = max(1, st.steps)
    return {
        "leg": "steady_state",
        "seqs": seqs,
        "prompt": prompt,
        "gen": gen,
        "bucket": bucket,
        "sync_tokens_per_sec": round(tok / wall_sync, 1),
        "pipelined_tokens_per_sec": round(tok / wall_pipe, 1),
        "speedup": round(wall_sync / wall_pipe, 2),
        "sync_p50_tbt_ms": round(float(np.percentile(lat_sync, 50)), 3),
        "sync_p99_tbt_ms": round(float(np.percentile(lat_sync, 99)), 3),
        "pipe_p50_tbt_ms": round(float(np.percentile(lat_pipe, 50)), 3),
        "pipe_p99_tbt_ms": round(float(np.percentile(lat_pipe, 99)), 3),
        "outputs_equal": outs_pipe == outs_sync,
        # the tentpole invariant: one int32 row per bucket slot per step
        "fetch_bytes_per_step": st.fetch_bytes_per_step,
        "fetch_is_token_row": st.fetch_bytes_per_step == 4.0 * bucket,
        "dispatch_ms_per_step": round(st.dispatch_ms / n, 3),
        "host_build_ms_per_step": round(st.host_build_ms / n, 3),
        "fetch_drain_ms_per_step": round(st.fetch_drain_ms / n, 3),
        "bubble_ms_per_step": round(st.bubble_ms / n, 3),
        "compiles_during_timed_runs": compiles,
    }


def _spec_select_prompts(engine, vocab: int, seqs: int, prompt: int,
                         rng: np.random.RandomState, candidates: int = 16,
                         probe_steps: int = 10):
    """Seeded search for REPETITIVE-regime prompts: tiled short phrases
    whose greedy continuation (on this random-init bench model) settles
    into loops the n-gram proposer can ride — the CPU-box analog of the
    templated/boilerplate traffic speculative decoding targets on a real
    model (a random-init model has no natural templated register, so the
    bench selects for the regime instead of pretending one exists). The
    probe runs SHORT spec bursts on the warmed grid and keeps the prompts
    with the most emitted tokens per verify step; selection is seeded and
    UNTIMED, and the byte-equality gate downstream is independent of it."""
    from deepspeed_tpu.inference.v2.spec import SpecDecodePipeline
    scored = []
    uid = 60_000
    for c0 in range(0, candidates, seqs):
        uids, prompts = [], []
        for _ in range(min(seqs, candidates - c0)):
            phrase = rng.randint(0, vocab,
                                 size=(int(rng.randint(3, 8)),)).astype(np.int32)
            p = np.tile(phrase, -(-prompt // len(phrase)))[:prompt]
            uids.append(uid)
            prompts.append(p)
            uid += 1
        engine._put_nofetch(uids, prompts)
        pipe = SpecDecodePipeline(engine, uids)
        head = pipe.run(probe_steps)
        # score the LOOP REGIME (the probe's tail): early steps measure the
        # cold ramp every prompt pays once, not how hard the loop sustains
        tail = pipe.run(probe_steps)
        engine.flush(uids)
        for p, toks in zip(prompts, tail):
            scored.append((len(toks), p))
        del head
    scored.sort(key=lambda x: -x[0])
    return [p for _, p in scored[:seqs]]


def run_spec(on_tpu: bool, smoke: bool, seqs: int = 4, prompt: int = 48,
             gen: int = 128, k: int = 15, reps: int = 3, seed: int = 0):
    """The speculative-decoding leg (docs/SERVING.md "Speculative
    decoding"): the SAME warmed engine generates ``gen`` greedy tokens per
    sequence through (a) the spec-off ``DecodePipeline`` (the PR 3
    baseline) and (b) the draft-and-verify ``SpecDecodePipeline``, over two
    workloads:

    - ``repetitive``: prompts selected (seeded, untimed) so greedy
      continuations loop — the templated-text regime prompt-lookup
      drafting targets; gates tok/s ratio >= the acceptance bar.
    - ``natural``: random prompts — low acceptance by construction on a
      random-init model; reported for the acceptance-economics curve, no
      speed bar (adaptive k backoff keeps the cost near 1x).

    Gates (every rep): byte-identical greedy streams spec-on vs spec-off,
    zero engine compiles in timed phases (the (bucket, k) verify grid rides
    warmup), and allocator free blocks back to baseline after every leg
    (reject-heavy runs exercise ``rollback_reserved``). Legs alternate
    off/on per rep; the ratio gate compares medians across reps."""
    from deepspeed_tpu.inference.v2.pipeline import DecodePipeline
    from deepspeed_tpu.inference.v2.spec import SpecDecodePipeline
    if smoke:
        gen, reps = min(gen, 32), 1
    # ctx slack must cover the WORST-case speculative reservation: the
    # selection probe's two back-to-back 10-step runs (a perfectly-looping
    # candidate — the exact regime the probe selects for — emits
    # 10*(k+1) in run one and run two still reserves 10*(k+1)+1 up
    # front), plus the timed legs' 8-step chunks
    engine, vocab = build_engine(on_tpu, seqs=seqs, prompt=prompt, gen=gen,
                                 warmup=True, warmup_bursts=False,
                                 spec_k=k,
                                 ctx_slack=(2 * 10 + 8) * (k + 1) + 16)
    rng = np.random.RandomState(seed)
    natural = [rng.randint(0, vocab, size=(prompt,)).astype(np.int32)
               for _ in range(seqs)]
    repetitive = _spec_select_prompts(engine, vocab, seqs, prompt, rng,
                                      candidates=seqs if smoke else 4 * seqs)
    uid_base = [80_000]

    def prefill(prompts):
        uid_base[0] += seqs
        uids = list(range(uid_base[0], uid_base[0] + seqs))
        engine._put_nofetch(uids, prompts)
        return uids

    def off_leg(prompts):
        uids = prefill(prompts)
        pipe = DecodePipeline(engine, uids)
        t0 = time.time()
        out = pipe.run(gen)
        wall = time.time() - t0
        engine.flush(uids)
        return [list(map(int, row)) for row in out], wall

    def spec_leg(prompts):
        uids = prefill(prompts)
        engine.spec_stats.reset()
        pipe = SpecDecodePipeline(engine, uids)
        outs = {u: [] for u in uids}

        def cb(j, run_uids, toks):
            stop = []
            for i, u in enumerate(run_uids):
                if len(outs[u]) >= gen:
                    continue
                outs[u].extend(int(t) for t in toks[i])
                if len(outs[u]) >= gen:
                    stop.append(u)
            return stop

        t0 = time.time()
        while pipe.uids:
            pipe.run(8, on_tokens=cb)
        wall = time.time() - t0
        engine.flush(uids)
        return [outs[u][:gen] for u in uids], wall

    ok = True
    results = []
    for leg, prompts in (("repetitive", repetitive), ("natural", natural)):
        # untimed warm pass for each loop shape
        off_leg(prompts)
        spec_leg(prompts)
        rep_out = []
        for r in range(reps):
            free0 = engine.free_blocks
            c0 = engine.compiles
            ref, wall_off = off_leg(prompts)
            got, wall_on = spec_leg(prompts)
            st = engine.spec_stats
            out = {
                "leg": "spec", "workload": leg, "rep": r,
                "seqs": seqs, "prompt": prompt, "gen": gen, "k": k,
                "spec_off_tok_s": round(seqs * gen / wall_off, 1),
                "spec_on_tok_s": round(seqs * gen / wall_on, 1),
                "ratio": round(wall_off / wall_on, 3),
                "acceptance_rate": round(st.acceptance_rate, 3),
                "tokens_per_step": round(st.tokens_per_step, 2),
                "draft_ms_per_step": round(st.draft_ms / max(1, st.steps), 3),
                "outputs_equal": got == ref,
                "compiles_during_timed": engine.compiles - c0,
                "free_blocks_at_baseline": engine.free_blocks == free0,
            }
            rep_out.append(out)
            print(json.dumps(out), flush=True)
            if not out["outputs_equal"] or out["compiles_during_timed"] != 0 \
                    or not out["free_blocks_at_baseline"]:
                ok = False
        results.append((leg, rep_out))
    med = {leg: float(np.median([x["ratio"] for x in outs]))
           for leg, outs in results}
    # the acceptance bar: repetitive-text decode tok/s over the spec-off
    # pipeline (ROADMAP 1.8x on TPU; 1.5x floor on the 2-core CPU box where
    # the drained verify step shares two cores with the host loop). Smoke
    # gates correctness only — at smoke sizes throughput is noise.
    bar = 1.0 if smoke else 1.5
    gate = med["repetitive"] >= bar if not smoke else True
    print(json.dumps({"gate": "spec_decode_speedup", "ok": bool(gate),
                      "median_ratio": med, "bar": bar, "reps": reps}),
          flush=True)
    return ok and gate


def build_frontend_engine(on_tpu: bool, pool_blocks: int, ctx: int,
                          rows: int = 4, block_size: int = 16,
                          prefix_cache: bool = False, lora: dict = None):
    """A warmed engine sized so the frontend workload SATURATES the KV pool
    (the regime preemption policy differentiates in): a deliberately small
    page pool, the full pow2 decode grid pre-compiled. ``prefix_cache``
    turns the radix tree on (the --router leg's routing substrate);
    ``lora`` enables the adapter pool (the --lora leg — warmup then also
    pre-compiles the (bucket, rank-bucket) program ladder)."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    if on_tpu:
        layers, hidden, heads, vocab = 12, 1536, 12, 32000
    else:
        layers, hidden, heads, vocab = 2, 64, 4, 256
    cfg = LlamaConfig(vocab_size=vocab, hidden_size=hidden,
                      intermediate_size=hidden * 4, num_hidden_layers=layers,
                      num_attention_heads=heads, num_key_value_heads=heads,
                      max_position_embeddings=ctx,
                      dtype=jnp.bfloat16 if on_tpu else jnp.float32)
    model = LlamaForCausalLM(cfg)
    params = jax.jit(model.init)(
        jax.random.PRNGKey(0),
        {"input_ids": jnp.zeros((1, 8), jnp.int32)})["params"]
    econf = {"state_manager": {"max_tracked_sequences": 4 * rows,
                               "max_ragged_sequence_count": rows,
                               "max_ragged_batch_size": 128 + rows,
                               "prefill_chunk_size": 32,
                               "max_context": ctx},
             "kv_cache": {"block_size": block_size,
                          "num_blocks": pool_blocks},
             "compile": {"warmup": True}}
    if prefix_cache:
        econf["prefix_cache"] = {"enabled": True}
    if lora:
        econf["lora"] = dict(lora, enabled=True)
    if not on_tpu:
        econf["dtype"] = jnp.float32
    engine = InferenceEngineV2(model=model, model_parameters=params,
                               config=econf)
    return engine, vocab


def _frontend_classes():
    # interactive outranks batch; its SLOs are meaningful on this box, batch
    # SLOs are loose (batch work tolerates preemption — that is the point)
    return [{"name": "interactive", "priority": 2,
             "ttft_slo_ms": 2500.0, "tbt_slo_ms": 400.0},
            {"name": "batch", "priority": 0,
             "ttft_slo_ms": 60000.0, "tbt_slo_ms": 20000.0}]


def _serve_plain(engine, uid, prompt, gen):
    """Direct PLAIN-pipeline reference serve — explicitly DecodePipeline,
    NOT the spec-aware ``engine.decode_pipeline`` factory: the
    bit-identical-programs side of the byte gates that serve their
    frontends with ``serving.spec = False`` (run_kv_dtype's gate
    taxonomy)."""
    from deepspeed_tpu.inference.v2.pipeline import DecodePipeline
    engine._put_nofetch([uid], [np.asarray(prompt, np.int32)])
    out = DecodePipeline(engine, [uid]).run(gen)
    engine.flush([uid])
    return [int(t) for t in out[0]]


def _forced_preempt_cycle(engine, frontend, vocab, rng, *, low_prompt=24,
                          low_new=48, grow_iters=40, grown=None,
                          hi_prompt=96, finish_iters=300, byte_check=False):
    """One deterministic preempt-offload-restore cycle, step()-driven (no
    thread): two batch requests decode until ``grown`` says their KV
    growth has pressured the pool (default: too few free blocks for an
    interactive arrival), which then preempts one. ``byte_check=True``
    additionally replays all three streams through direct DecodePipeline
    runs — the --kv-dtype leg's gate that the packed value+scale payload
    round trip preserved the stream. Returns (ok, detail)."""
    if grown is None:
        def grown(lows):
            return engine.scheduler.available_blocks < 8
    lows = [frontend.submit(rng.randint(0, vocab,
                                        size=(low_prompt,)).astype(np.int32),
                            priority="batch", max_new_tokens=low_new)
            for _ in range(2)]
    for _ in range(grow_iters):              # let batch KV grow into the pool
        frontend.step()
        if grown(lows):
            break
    h_hi = frontend.submit(rng.randint(0, vocab,
                                       size=(hi_prompt,)).astype(np.int32),
                           priority="interactive", max_new_tokens=8)
    for _ in range(finish_iters):
        if h_hi.finished and all(h.finished for h in lows):
            break
        frontend.step()
    ok = (h_hi.status == "finished"
          and all(h.status == "finished" for h in lows)
          and frontend.stats.preemptions >= 1
          and frontend.stats.restores >= 1
          and frontend.stats.offload_bytes > 0)
    detail = {"preemptions": frontend.stats.preemptions,
              "restores": frontend.stats.restores,
              "offload_bytes": frontend.stats.offload_bytes,
              "lo_tokens": [len(h.tokens) for h in lows],
              "hi_tokens": len(h_hi.tokens)}
    if byte_check:
        equal = 0
        for i, h in enumerate(lows + [h_hi]):
            equal += _serve_plain(engine, 88_000 + i, h.prompt,
                                  len(h.tokens)) == h.tokens
        ok = ok and equal == 3
        detail["streams_equal"] = equal
        detail["streams_checked"] = 3
    return ok, detail


def run_frontend(on_tpu: bool, smoke: bool, rate: float, duration: float,
                 seed: int = 0, reps: int = 3):
    """The SLO-aware frontend leg (docs/SERVING.md "Frontend"): a seeded
    Poisson mixed-priority workload replayed identically against each
    preemption policy on ONE warmed engine, gating

      - byte-equality: every completed stream == a direct decode_pipeline
        run of the same prompt (offload + reject-only modes; recompute
        victims legitimately re-prefill through a different kernel path),
      - zero engine compiles during every timed phase (the pow2 grid +
        warmed page round-trip absorb admission, preemption and restore),
      - one forced preempt-offload-restore cycle (deterministic, pre-replay),
      - goodput-under-SLO: median over ``reps`` replays, offload >=
        recompute and >= reject-only (full runs only; the default rate
        clearly OVERSUBSCRIBES the pool — token demand ~1.7x measured
        capacity — so every rep runs in the triage regime preemption policy
        exists for, and requests unfinished at the drain deadline are
        cancelled, scoring zero).

    Smoke runs the offload mode only, one rep (<60 s on a 2-core CPU box)."""
    from deepspeed_tpu.inference.v2.serving import (PoissonLoadGen,
                                                    WorkloadComponent,
                                                    goodput_report, replay)
    engine, vocab = build_frontend_engine(on_tpu, pool_blocks=14, ctx=160)
    mix = [WorkloadComponent("interactive", 4.0, [16, 32], [8, 16, 24]),
           WorkloadComponent("batch", 1.0, [48], [96])]
    arrivals = PoissonLoadGen(rate=rate, mix=mix, vocab=vocab,
                              seed=seed).arrivals(duration=duration)
    modes = ["offload"] if smoke else ["offload", "recompute", "none"]
    if smoke:
        reps = 1
    results = {m: [] for m in modes}
    forced = None
    ok = True
    # reps interleave the modes (off/rec/none, off/rec/none, ...) so slow
    # drift on a shared box lands on every mode, not one — the same
    # alternation discipline the trace-overhead bench uses
    for r in range(reps):
        for mode in modes:
            serving = {"classes": _frontend_classes(), "decode_slice": 4,
                       "preemption": mode, "idle_wait_s": 0.002}
            fe = engine.serving_frontend(config=serving)
            c0 = engine.compiles
            if mode == "offload" and r == 0:
                rng = np.random.RandomState(seed + 1)
                f_ok, forced = _forced_preempt_cycle(engine, fe, vocab, rng)
                forced["ok"] = f_ok
            t0 = time.time()
            fe.start()
            handles = replay(fe, arrivals)
            fe.drain(timeout=2.5 * duration)
            wall = time.time() - t0
            fe.close()           # past-deadline stragglers cancel: 0 goodput
            compiles = engine.compiles - c0
            rep = goodput_report(handles, wall)
            # byte-equality: finished streams vs direct pipeline runs of the
            # same prompts on the same engine (preempt-offloaded included)
            finished = [h for h in handles if h.status == "finished"]
            check = finished[:24] if smoke else finished[:48]
            preempted_checked = equal = skipped = 0
            for h in check:
                if mode == "recompute" and h.preemptions:
                    skipped += 1
                    continue
                engine._put_nofetch([77_000 + h.uid], [h.prompt])
                out = engine.decode_pipeline(
                    [77_000 + h.uid]).run(len(h.tokens))
                engine.flush([77_000 + h.uid])
                if [int(t) for t in out[0]] == h.tokens:
                    equal += 1
                    preempted_checked += bool(h.preemptions)
            checked = len(check) - skipped
            out = {
                "leg": "frontend", "mode": mode, "rep": r, "rate": rate,
                "duration": duration, "arrivals": len(arrivals),
                "preemptions": fe.stats.preemptions,
                "recompute_preemptions": fe.stats.recompute_preemptions,
                "restores": fe.stats.restores,
                "offload_bytes": fe.stats.offload_bytes,
                "forced_cycle": forced if (mode == "offload" and r == 0)
                else None,
                "streams_checked": checked,
                "streams_equal": equal,
                "preempted_streams_checked": preempted_checked,
                "outputs_equal": equal == checked,
                "compiles_during_timed": compiles,
                **rep,
            }
            results[mode].append(out)
            print(json.dumps(out), flush=True)
            if mode != "recompute" and not out["outputs_equal"]:
                ok = False
            if compiles != 0:
                ok = False
    if not forced["ok"]:
        print(json.dumps({"gate": "forced_preempt_offload_restore",
                          "ok": False}), flush=True)
        ok = False
    if not smoke:
        med = {m: float(np.median([x["goodput_tokens_per_sec"]
                                   for x in results[m]])) for m in modes}
        gate = med["offload"] >= med["recompute"] \
            and med["offload"] >= med["none"]
        print(json.dumps({"gate": "goodput_under_slo", "ok": gate,
                          "median_goodput": med, "reps": reps}), flush=True)
        ok = ok and gate
    return ok


def _register_bench_adapters(engine, ranks):
    """Register one seeded random adapter per entry of ``ranks`` (names
    ``ad0, ad1, ...``); deltas are small (~2% weight scale) so streams stay
    well-formed but DO diverge from base decodes."""
    from deepspeed_tpu.module_inject.lora import load_lora_adapter
    spec = engine.spec
    din = spec.hidden_size
    douts = {"q": spec.num_heads * spec.head_dim,
             "k": spec.num_kv_heads * spec.head_dim,
             "v": spec.num_kv_heads * spec.head_dim,
             "o": spec.hidden_size}
    names = []
    for i, r in enumerate(ranks):
        g = np.random.RandomState(1000 + i)
        state = {"alpha": float(r)}
        for t in engine.config.lora.targets:
            state[t] = {
                "A": (g.standard_normal((din, r)) * 0.02).astype(np.float32),
                "B": (g.standard_normal((r, douts[t])) * 0.02).astype(
                    np.float32)}
        name = f"ad{i}"
        load_lora_adapter(engine, name, state)
        names.append(name)
    return names


def _serve_lora_plain(engine, uid, prompt, gen, adapter):
    """Direct plain-pipeline reference serve under an adapter binding —
    the byte-equality oracle for the --lora leg's mixed-tenant streams."""
    from deepspeed_tpu.inference.v2.pipeline import DecodePipeline
    if adapter is not None:
        engine.lora.acquire(uid, adapter)
    try:
        engine._put_nofetch([uid], [np.asarray(prompt, np.int32)])
        out = DecodePipeline(engine, [uid]).run(gen)
        engine.flush([uid])
    finally:
        if adapter is not None:
            engine.lora.release(uid)
    return [int(t) for t in out[0]]


def _lora_pool_baseline(engine):
    """(ok, detail): adapter pool consistency at idle — every refcount 0,
    free + resident pages account for the whole pool, no pinned swap
    buffers outstanding."""
    reg = engine.lora
    resident = sum(reg.rank(n) for n in reg.names if reg.is_resident(n))
    free = reg.pool.free_pages
    detail = {"free_pages": free, "resident_pages": resident,
              "pool_pages": reg.pool.num_pages,
              "refcounts": {n: reg.refcount(n) for n in reg.names},
              "swap_outstanding": reg.swap.outstanding}
    ok = (free + resident == reg.pool.num_pages
          and all(v == 0 for v in detail["refcounts"].values())
          and reg.swap.outstanding == 0)
    return ok, detail


def run_lora(on_tpu: bool, smoke: bool, rate: float, duration: float,
             seed: int = 0, reps: int = 3):
    """The multi-tenant LoRA leg (BENCH_r18; docs/SERVING.md "Multi-tenant
    LoRA"): a seeded Poisson mix where arrivals draw tenants from MORE
    registered adapters than the adapter pool holds at once — admission
    faults cold adapters in and LRU-evicts idle ones while one ragged
    decode batch mixes tenants. Gates, every rep:

      - byte-equality: finished mixed-batch streams == direct per-adapter
        DecodePipeline runs on the same warmed engine,
      - zero engine compiles during every timed phase (the warmed
        (bucket, rank-bucket) ladder absorbs adapter churn),
      - allocator AND adapter pool at baseline after drain (refcounts 0,
        free + resident pages == pool, no pinned buffers outstanding),

    and (full runs) goodput-under-SLO >= 1.5x a NAIVE one-adapter-at-a-time
    baseline: the same arrivals grouped by adapter and each group served
    sequentially to drain (group-relative arrival stamps — generous to the
    baseline, which never pays cross-tenant queueing), on the same engine.
    Spec decode stays OFF: one variable (grouped adapter matmul) per leg."""
    import dataclasses
    from deepspeed_tpu.inference.v2.serving import (PoissonLoadGen,
                                                    WorkloadComponent,
                                                    goodput_report, replay)
    engine, vocab = build_frontend_engine(
        on_tpu, pool_blocks=20, ctx=160,
        lora={"pool_pages": 8, "max_rank": 4, "swap_buffers": 16})
    # 4 adapters totalling 13 pages against an 8-page pool: at most two of
    # the rank-4 tenants are resident with a third's pages in flight, so a
    # saturating mix MUST evict/restore to serve everyone
    adapters = _register_bench_adapters(engine, ranks=[4, 4, 3, 2])
    mix = [WorkloadComponent("interactive", 3.0, [16, 32], [8, 16],
                             adapter_id=adapters),
           WorkloadComponent("interactive", 1.0, [16], [8]),   # base tenant
           WorkloadComponent("batch", 1.0, [32], [24],
                             adapter_id=adapters[0])]
    arrivals = PoissonLoadGen(rate=rate, mix=mix, vocab=vocab,
                              seed=seed).arrivals(duration=duration)
    serving = {"classes": _frontend_classes(), "decode_slice": 4,
               "preemption": "offload", "idle_wait_s": 0.002,
               "spec": False}
    if smoke:
        reps = 1
    ok = True
    mixed_good, naive_good = [], []
    for r in range(reps):
        kv_free0 = engine.allocator.free_blocks
        # -- mixed multi-tenant replay (the subsystem under test) ---------
        fe = engine.serving_frontend(config=serving)
        c0 = engine.compiles
        t0 = time.time()
        fe.start()
        handles = replay(fe, arrivals)
        fe.drain(timeout=2.5 * duration + 20)
        wall = time.time() - t0
        fe.close()
        compiles_mixed = engine.compiles - c0
        rep = goodput_report(handles, wall)
        faults = engine.lora.stats
        # byte-equality: mixed-batch streams vs direct per-adapter serves
        finished = [(h, a) for h, a in zip(handles, arrivals)
                    if h.status == "finished" and h.tokens]
        check = finished[:16] if smoke else finished[:32]
        c1 = engine.compiles
        equal = 0
        for i, (h, a) in enumerate(check):
            got = _serve_lora_plain(engine, 91_000 + i, h.prompt,
                                    len(h.tokens), a.adapter)
            equal += got == h.tokens
        compiles_ref = engine.compiles - c1
        # settle the swap pool before the baseline: adapters that happen to
        # sit EVICTED here legitimately hold pinned buffers, which the leak
        # check would misread as outstanding (the --lora --smoke flake)
        engine.lora.drain_swap()
        pool_ok, pool_detail = _lora_pool_baseline(engine)
        kv_ok = engine.allocator.free_blocks == kv_free0
        out = {
            "leg": "lora", "mode": "mixed", "rep": r, "rate": rate,
            "duration": duration, "arrivals": len(arrivals),
            "adapters": len(adapters),
            "adapter_pool_pages": engine.lora.pool.num_pages,
            "adapter_faults": sum(c.faults
                                  for c in faults.adapters.values()),
            "adapter_evictions": sum(c.evictions
                                     for c in faults.adapters.values()),
            "adapter_hit_fraction": round(faults.hit_fraction, 3),
            "streams_checked": len(check), "streams_equal": equal,
            "outputs_equal": equal == len(check),
            "compiles_during_timed": compiles_mixed + compiles_ref,
            "allocator_at_baseline": kv_ok,
            "adapter_pool_at_baseline": pool_ok,
            "adapter_pool": pool_detail,
            **rep,
        }
        print(json.dumps(out), flush=True)
        mixed_good.append(rep["goodput_tokens_per_sec"])
        ok = ok and out["outputs_equal"] and kv_ok and pool_ok \
            and out["compiles_during_timed"] == 0
        # -- naive one-adapter-at-a-time baseline -------------------------
        groups = {}
        for a in arrivals:
            groups.setdefault(a.adapter, []).append(a)
        naive_wall = 0.0
        naive_tokens = 0
        compiles_naive = 0
        for key in sorted(groups, key=lambda k: groups[k][0].t):
            grp = [dataclasses.replace(a, t=a.t - groups[key][0].t)
                   for a in groups[key]]
            fe = engine.serving_frontend(config=serving)
            c0 = engine.compiles
            t0 = time.time()
            fe.start()
            hs = replay(fe, grp)
            fe.drain(timeout=2.5 * duration + 20)
            naive_wall += time.time() - t0
            fe.close()
            compiles_naive += engine.compiles - c0
            naive_tokens += goodput_report(hs, 1.0)["good_tokens"]
        naive = round(naive_tokens / naive_wall, 1)
        out = {"leg": "lora", "mode": "naive_sequential", "rep": r,
               "groups": len(groups), "wall_s": round(naive_wall, 2),
               "goodput_tokens_per_sec": naive,
               "compiles_during_timed": compiles_naive}
        print(json.dumps(out), flush=True)
        naive_good.append(naive)
        ok = ok and compiles_naive == 0
    if not smoke:
        med_m = float(np.median(mixed_good))
        med_n = float(np.median(naive_good))
        gate = med_m >= 1.5 * med_n
        print(json.dumps({"gate": "lora_goodput_vs_naive", "ok": gate,
                          "median_mixed": med_m, "median_naive": med_n,
                          "required_ratio": 1.5,
                          "ratio": round(med_m / max(med_n, 1e-9), 2)}),
              flush=True)
        ok = ok and gate
    return ok


def _kv_dtype_layout(on_tpu: bool):
    """(layers, hidden, heads, kv_heads, vocab) for the --kv-dtype leg."""
    if on_tpu:
        return 12, 1536, 12, 12, 32000
    return 2, 256, 2, 2, 256


def _kv_dtype_bpb(on_tpu: bool, kvq: bool) -> int:
    """bytes_per_block at the leg's pool layout — sizes the shared byte
    budget and the capacity thresholds from the SAME math the engine
    pools use, so the leg works on both the CPU (fp32) and TPU (bf16)
    model shapes."""
    import jax.numpy as jnp
    from deepspeed_tpu.inference.v2.ragged.kv_cache import KVCacheConfig
    layers, hidden, heads, kvh, _ = _kv_dtype_layout(on_tpu)
    return KVCacheConfig(num_layers=layers, num_kv_heads=kvh,
                         head_dim=hidden // heads, block_size=64,
                         num_blocks=1,
                         dtype=jnp.bfloat16 if on_tpu else jnp.float32,
                         quantized=kvq).bytes_per_block()


def build_kv_dtype_engine(on_tpu: bool, kvq: bool, budget_bytes: int,
                          rows: int = 4, ctx: int = 256, spec_k: int = 3,
                          num_blocks: int = None):
    """A warmed engine for the --kv-dtype leg: head_dim-128 model (the
    int8 alignment gate), prefix cache AND spec decode ON — the full
    production composition the former build-time refusals forbade — and
    the KV pool sized from ONE shared HBM byte budget, so the int8 pool's
    extra blocks ARE the capacity win the goodput gate measures."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.inference.v2.ragged.kv_cache import KVCacheConfig
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    # CPU layout: hidden/intermediate/H*D all <= 256 ON PURPOSE — XLA CPU
    # runs M=1 matmuls through a GEMV kernel whose reduction order differs
    # from the M>=2 GEMM path once K reaches 512 (measured: row 0 of a
    # [1,512]x[512,512] f32 dot differs from the same row inside a [4,512]
    # batch by ~6e-5), so a solo-rerun reference can never byte-match a
    # dynamically-batched serving stream at that width — every reduction
    # dim stays <= 256 so the leg's byte gates compare bit-identical math
    # (head_dim stays 128 for the int8 gate)
    layers, hidden, heads, kvh, vocab = _kv_dtype_layout(on_tpu)
    block_size = 64                       # kvh * 64 lane-aligns both configs
    cfg = LlamaConfig(vocab_size=vocab, hidden_size=hidden,
                      intermediate_size=hidden, num_hidden_layers=layers,
                      num_attention_heads=heads, num_key_value_heads=kvh,
                      max_position_embeddings=ctx,
                      dtype=jnp.bfloat16 if on_tpu else jnp.float32)
    model = LlamaForCausalLM(cfg)
    params = jax.jit(model.init)(
        jax.random.PRNGKey(0),
        {"input_ids": jnp.zeros((1, 8), jnp.int32)})["params"]
    probe = KVCacheConfig(num_layers=layers, num_kv_heads=kvh,
                          head_dim=hidden // heads, block_size=block_size,
                          num_blocks=1,
                          dtype=jnp.bfloat16 if on_tpu else jnp.float32,
                          quantized=kvq)
    if num_blocks is None:
        num_blocks = max(4, budget_bytes // probe.bytes_per_block())
    econf = {"state_manager": {"max_tracked_sequences": 4 * rows,
                               "max_ragged_sequence_count": rows,
                               "max_ragged_batch_size": 128 + rows,
                               "prefill_chunk_size": 32,
                               "max_context": ctx},
             "kv_cache": {"block_size": block_size,
                          "num_blocks": num_blocks},
             "prefix_cache": {"enabled": True},
             "spec_decode": {"enabled": True, "k": spec_k},
             "compile": {"warmup": True}}
    if kvq:
        econf["kv_quant"] = {"enabled": True}
    if not on_tpu:
        econf["dtype"] = jnp.float32
    engine = InferenceEngineV2(model=model, model_parameters=params,
                               config=econf)
    return engine, vocab, num_blocks


def run_kv_dtype(on_tpu: bool, smoke: bool, rate: float, duration: float,
                 seed: int = 0, reps: int = 3):
    """The --kv-dtype int8 leg (docs/SERVING.md "Quantized KV"): the SAME
    seeded Poisson workload against an fp32 (bf16 on TPU) pool and an int8
    pool sized from ONE byte budget, both engines with prefix cache AND
    spec decode enabled (the composition this PR unlocked), gating

      - BYTE tier (int8 engine): cache-hit re-serves byte-identical to the
        cold serve (radix reuse + COW scale-tile adoption), spec-on ==
        spec-off streams, one forced preempt-offload-restore cycle with
        the restored stream checked, and every checked frontend stream ==
        a direct decode_pipeline run of the same prompt;
      - zero engine compiles during every timed phase (warmup covers the
        decode grid, the (bucket, k) verify grid and the packed page-op
        round trip);
      - the capacity win: kv bytes/token measurably below the fp pool
        (the monitor gauge — the HBM-stream claim at this layout) and the
        int8 pool holding more blocks at the same byte budget — >= 2x vs
        the CPU fp32 pool, >= 1.7x vs the TPU bf16 pool (half-width
        elements cap the win at <2x once scale tiles ride on top);
      - the RESIDENCY gate (full runs, every rep, compute-independent):
        the same replay that forces the fp pool to preempt-churn (it
        cannot hold the workload's KV working set at this byte budget)
        runs with ZERO preemptions on the int8 pool — the ~3.5x block
        density holding the working set resident is the capacity fact
        the goodput conversion rests on;
      - goodput-under-SLO medians are REPORTED on CPU and GATED
        (int8 >= fp) on TPU only: this 2-core interpret-mode box is
        compute-bound, so walls measure interpret dequant overhead and
        spec-draft scheduling noise, not the HBM-bound serving regime
        (measured here: every run completes every request within SLO and
        goodput differences are pure wall noise) — the regime the int8
        decode kernel's 1.27x and the resident-capacity doubling convert
        in is the TPU one the gate targets.

    int8-vs-fp streams are NOT compared byte-wise — quantization changes
    numerics by design; the cross-dtype tier is the prefill-logits rtol
    gate (documented in docs/SERVING.md). The timed replays serve with
    ``serving.spec = False`` (the plain pipeline) so every stream
    byte-check compares bit-identical programs and isolates
    ORCHESTRATION (admission/preemption/restore/cache): spec-on vs
    spec-off greedy streams agree only up to cross-kernel float noise
    (~1e-4/token argmax flips on this random-init model — measured; the
    gate-taxonomy line docs/SERVING.md draws), so the spec x int8
    composition is byte-gated at its own deterministic scale (the
    spec_stream_equal gate here + tests/unit/test_kv_quant_stack.py +
    the --spec leg) rather than across thousands of replay tokens."""
    from deepspeed_tpu.inference.v2.serving import (PoissonLoadGen,
                                                    WorkloadComponent,
                                                    goodput_report, replay)
    # the shared budget: 6 fp blocks at the platform's pool layout (CPU
    # fp32: ~1.5 MB; TPU bf16: ~27 MB) — small enough that the batch
    # mixture's KV lifetime SATURATES the fp pool (constant preempt/
    # offload churn) while the denser int8 pool (~3.5x on fp32, ~1.9x on
    # bf16) holds the whole working set resident: the capacity regime the
    # goodput gate measures
    budget = 6 * _kv_dtype_bpb(on_tpu, kvq=False)
    engines = {}
    blocks = {}
    for name, kvq in (("fp", False), ("int8", True)):
        e, vocab, nb = build_kv_dtype_engine(on_tpu, kvq, budget)
        _force_paged(e)
        engines[name], blocks[name] = e, nb
    ok = True
    rng = np.random.RandomState(seed)
    bpt = {n: e.kv.config.bytes_per_block() / e.kv.config.block_size
           for n, e in engines.items()}

    # ---- cross-dtype rtol tier: prefill logits ------------------------ #
    toks = [rng.randint(0, vocab, size=(24,)).astype(np.int32)
            for _ in range(2)]
    lf = np.asarray(engines["fp"].put([1, 2], [t.copy() for t in toks]),
                    np.float32)
    lq = np.asarray(engines["int8"].put([1, 2], [t.copy() for t in toks]),
                    np.float32)
    for e in engines.values():
        e.flush([1, 2])
    rtol_gate = float(np.max(np.abs(lf - lq))) < 0.05 * float(np.max(np.abs(lf)))

    # ---- byte tier on the int8 engine --------------------------------- #
    eq = engines["int8"]

    prefix = rng.randint(0, vocab, size=(96,))
    tail = rng.randint(0, vocab, size=(8,))
    prompt = np.concatenate([prefix, tail]).astype(np.int32)
    cold = _serve_plain(eq, 900, prompt, 12)
    hits0 = eq.prefix_cache.stats.hits
    warm = _serve_plain(eq, 901, prompt, 12)
    cache_gate = warm == cold and eq.prefix_cache.stats.hits > hits0

    from deepspeed_tpu.inference.v2.spec import SpecDecodePipeline
    p2 = rng.randint(0, vocab, size=(20,)).astype(np.int32)
    ref = _serve_plain(eq, 902, p2, 12)
    eq._put_nofetch([903], [p2.copy()])
    sp = SpecDecodePipeline(eq, [903])
    got = []
    while sp.uids and len(got) < 12:
        for row in sp.run(2):
            got.extend(int(t) for t in row)
    eq.flush([903])
    spec_gate = got[:12] == ref

    # ---- forced preempt-offload-restore on a POOL-SATURATED int8 engine
    # (the main int8 engine's whole point is that it does NOT saturate):
    # a quarter-budget pool forces admission to offload a decoding batch
    # victim's packed value+scale pages and restore them byte-exactly,
    # with zero compiles (warmup covers the page-op grid)
    ef, _, _ = build_kv_dtype_engine(on_tpu, True, budget // 4)
    _force_paged(ef)
    fe_f = ef.serving_frontend(config={"classes": [
        {"name": "interactive", "priority": 2,
         "ttft_slo_ms": 60000.0, "tbt_slo_ms": 20000.0},
        {"name": "batch", "priority": 0,
         "ttft_slo_ms": 60000.0, "tbt_slo_ms": 20000.0}],
        "decode_slice": 4, "spec": False, "idle_wait_s": 0.002})
    cf0 = ef.compiles
    f_ok, forced = _forced_preempt_cycle(
        ef, fe_f, vocab, np.random.RandomState(seed + 1),
        low_prompt=150, low_new=60, grow_iters=80,
        # a batch victim must be DECODING when the interactive lands
        grown=lambda lows: any(len(h.tokens) >= 4 for h in lows),
        hi_prompt=128, finish_iters=900, byte_check=True)
    forced["ok"] = f_ok
    forced["compiles"] = ef.compiles - cf0
    fe_f.close()
    _unforce_paged(ef)
    del ef
    if forced["compiles"] != 0:
        forced["ok"] = f_ok = False

    # ---- Poisson replays: same arrivals, each pool -------------------- #
    # SLOs sized to this box's triage window: loose enough that shedding
    # and goodput track CAPACITY (the pools' difference), not interpret-
    # mode prefill latency; the batch mixture's KV lifetime (~3 blocks of
    # the 6-block fp pool each) is what saturates the fp side
    classes = [{"name": "interactive", "priority": 2,
                "ttft_slo_ms": 30000.0, "tbt_slo_ms": 5000.0},
               {"name": "batch", "priority": 0,
                "ttft_slo_ms": 120000.0, "tbt_slo_ms": 30000.0}]
    # spec=False: the replay's byte-checks compare BIT-IDENTICAL programs
    # (plain pipeline both sides — leg docstring); the spec x int8 gates
    # live above at their deterministic scale
    serving = {"classes": classes, "decode_slice": 4, "spec": False,
               "idle_wait_s": 0.002}
    mix = [WorkloadComponent("interactive", 3.0, [16, 24], [8, 12],
                             prefix_len=64),
           WorkloadComponent("batch", 2.0, [48], [160])]
    arrivals = PoissonLoadGen(rate=rate, mix=mix, vocab=vocab,
                              seed=seed).arrivals(duration=duration)
    if smoke:
        reps = 1
    results = {n: [] for n in engines}
    for r in range(reps):
        for name, e in engines.items():
            # each replay starts with a COLD radix tree (the router leg's
            # discipline): reps stay comparable and the byte-checks below
            # re-derive the same cache state the replay built
            _clear_prefix_caches([e])
            fe = e.serving_frontend(config=serving)
            c0 = e.compiles
            t0 = time.time()
            fe.start()
            handles = replay(fe, arrivals)
            fe.drain(timeout=3.0 * duration + 15.0)
            wall = time.time() - t0
            fe.close()
            compiles = e.compiles - c0
            rep = goodput_report(handles, wall)
            finished = [h for h in handles if h.status == "finished"]
            check = finished[:12] if smoke else finished[:32]
            equal = 0
            for i, h in enumerate(check):
                # plain pipeline both sides: bit-identical programs, the
                # comparison isolates orchestration (leg docstring)
                out = _serve_plain(e, 77_000 + 100 * r + i, h.prompt,
                                   len(h.tokens))
                equal += out == h.tokens
            ev = {k: v for k, v, _ in fe.stats.events()}
            out = {
                "leg": "kv_dtype", "pool": name, "rep": r, "rate": rate,
                "duration": duration, "arrivals": len(arrivals),
                "pool_blocks": blocks[name],
                "kv_bytes_per_token": bpt[name],
                "pool_dtype_bits": ev["serve/frontend/kv/pool_dtype_bits"],
                "preemptions": fe.stats.preemptions,
                "restores": fe.stats.restores,
                "streams_checked": len(check), "streams_equal": equal,
                "outputs_equal": equal == len(check),
                "compiles_during_timed": compiles,
                "forced_cycle": forced if (name == "int8" and r == 0)
                else None,
                **rep,
            }
            results[name].append(out)
            print(json.dumps(out), flush=True)
            if not out["outputs_equal"] or compiles != 0:
                ok = False
    for e in engines.values():
        _unforce_paged(e)

    # dtype-aware thresholds: int8 value bytes are 1/4 of an fp32 pool's
    # but only 1/2 of a bf16 pool's, and the padded f32 scale tiles ride
    # on top — a bf16 pool can NEVER meet the fp32-calibrated 2x/0.5x
    # bar (value bytes alone are exactly half), so the TPU leg gates at
    # the density its element width actually affords
    if on_tpu:
        min_blocks, max_bpt_frac = int(1.7 * blocks["fp"]), 0.58
    else:
        min_blocks, max_bpt_frac = 2 * blocks["fp"], 0.5
    capacity_gate = (blocks["int8"] >= min_blocks
                     and bpt["int8"] < max_bpt_frac * bpt["fp"])
    print(json.dumps({"gate": "kv_dtype_byte_tier", "ok": bool(
        cache_gate and spec_gate and forced["ok"]),
        "cache_hit_stream_equal": bool(cache_gate),
        "spec_stream_equal": bool(spec_gate),
        "forced_preempt_cycle": forced}), flush=True)
    print(json.dumps({"gate": "kv_dtype_rtol_tier", "ok": bool(rtol_gate),
                      "rtol": 0.05}), flush=True)
    print(json.dumps({"gate": "kv_dtype_capacity", "ok": bool(capacity_gate),
                      "pool_blocks": blocks,
                      "kv_bytes_per_token": bpt}), flush=True)
    ok = ok and cache_gate and spec_gate and forced["ok"] and rtol_gate \
        and capacity_gate
    if not smoke:
        # the RESIDENCY gate (compute-independent capacity fact): the fp
        # pool cannot hold this workload's KV working set at the shared
        # byte budget — it preempt-churns every rep — while the int8
        # pool's ~3.5x block density holds it RESIDENT (zero preemptions)
        fp_pressured = all(x["preemptions"] >= 1 for x in results["fp"])
        int8_resident = all(x["preemptions"] == 0 for x in results["int8"])
        gate = fp_pressured and int8_resident
        print(json.dumps({"gate": "kv_dtype_residency", "ok": bool(gate),
                          "fp_preemptions": [x["preemptions"]
                                             for x in results["fp"]],
                          "int8_preemptions": [x["preemptions"]
                                               for x in results["int8"]]}),
              flush=True)
        ok = ok and gate
        # goodput-under-SLO: gated in the HBM-bound regime (TPU) only; on
        # CPU interpret the walls measure dequant/scheduling artifacts of
        # the harness, not the serving stack (see the leg docstring)
        med = {n: float(np.median([x["goodput_tokens_per_sec"]
                                   for x in results[n]])) for n in engines}
        xgate = med["int8"] >= med["fp"]
        print(json.dumps({"gate": "kv_dtype_goodput_vs_fp",
                          "ok": bool(xgate) if on_tpu else None,
                          "gated": bool(on_tpu),
                          "median_goodput": med, "reps": reps}), flush=True)
        if on_tpu:
            ok = ok and xgate
    return ok


def _force_paged(engine):
    """Disable the packed pure-prefill fast path on one engine: a prefix-
    cache hit turns a from-zero prefill into a continuation, which ALWAYS
    takes the paged path, while a cold prompt takes the packed path — and
    the two kernels carry a benign per-path numerical variance (see
    run_shared_prefix). Holding the kernel path constant across every
    replica AND the direct-reference runs makes the router's byte-equality
    gate test exactly what routing changes: WHERE requests run and which KV
    pages back them."""
    orig = engine.scheduler.schedule_pass

    def no_fast_path():
        b = orig()
        if b is not None:
            b.pure_prefill = False
        return b

    engine.scheduler.schedule_pass = no_fast_path


def _unforce_paged(engine):
    # drop the instance attr (lookup falls back to the class method): the
    # wrapper's closure holds a bound method of the scheduler — a reference
    # cycle that would keep the engine's device KV pool alive until gc
    try:
        del engine.scheduler.schedule_pass
    except AttributeError:
        pass


def _clear_prefix_caches(engines):
    """Evict every cached page (all sequences are flushed between replays,
    so the whole tree is refcount-1) — each policy replay starts COLD, and
    the eviction deltas empty any registered router index."""
    for e in engines:
        pc = e.prefix_cache
        while pc is not None and pc.cached_blocks:
            if pc.evict(pc.cached_blocks) == 0:
                break


def _attribution_gate(handles):
    """SLO-miss attribution gate (docs/OBSERVABILITY.md): every finished
    request's phase ledger must TILE arrival..last-emission — its stints
    sum to the client-measured latency (TTFT + Σ TBT) within the shared
    ``attribution_epsilon`` (the SAME tolerance the serve/slo
    attr_consistent stat applies). Gated over ALL finished requests (a
    superset of the SLO-missed ones the acceptance bar names). Returns
    (checked, bad_records)."""
    from deepspeed_tpu.inference.v2.serving.frontend import \
        attribution_epsilon
    checked = 0
    bad = []
    for h in handles:
        if h.status != "finished":
            continue
        attr = h.attribution()
        client = attr["client_s"]
        if client is None:
            continue
        checked += 1
        if abs(attr["total_s"] - client) > attribution_epsilon(client):
            bad.append({"uid": h.uid, "migrated": h.migrated,
                        "client_s": round(client, 4),
                        "ledger_s": round(attr["total_s"], 4),
                        "phases": {k: round(v, 4)
                                   for k, v in attr["phases"].items()}})
    return checked, bad


def _migrated_chain_gate(handles):
    """Failover chain gate: every migrated FINISHED request must carry a
    ``migration`` stint on its ledger, and (tracing on) its flow chain —
    spans sharing its trace_id — must span >= 2 lanes including the
    health lane's migrate span: the hops survive the replica death.
    Returns (migrated_finished, ok_count, bad_uids)."""
    from deepspeed_tpu.monitor.trace import tracer as _tr
    migrated = [h for h in handles if h.status == "finished" and h.migrated]
    if not migrated:
        return 0, 0, []
    by_tid = {}
    if _tr.enabled:
        for kind, name, _t0, _t1, lane, args in _tr.iter_records():
            if kind == "X" and args and "trace_id" in args:
                by_tid.setdefault(args["trace_id"], set()).add((lane, name))
    ok = 0
    bad = []
    for h in migrated:
        good = any(p == "migration" for p, _, _ in h.timeline())
        if good and _tr.enabled:
            recs = by_tid.get(h.trace_id, set())
            good = (len({lane for lane, _ in recs}) >= 2
                    and any(n == "serve/health/migrate" for _, n in recs))
        if good:
            ok += 1
        else:
            bad.append(h.uid)
    return len(migrated), ok, bad


def _check_router_streams(engine, handles, limit, uid_base):
    """Byte-equality: finished router streams vs direct decode_pipeline
    runs of the same prompts on ``engine`` (same weights on every replica,
    forced-paged kernel path on both sides)."""
    finished = [h for h in handles if h.status == "finished"]
    check = finished[:limit]
    equal = 0
    for i, h in enumerate(check):
        uid = uid_base + i
        engine._put_nofetch([uid], [h.prompt])
        out = engine.decode_pipeline([uid]).run(len(h.tokens))
        engine.flush([uid])
        equal += [int(t) for t in out[0]] == h.tokens
    return len(check), equal


def run_router(on_tpu: bool, smoke: bool, seed: int = 0, reps: int = 3):
    """The multi-replica router leg (docs/SERVING.md "Multi-replica &
    disaggregation"), BENCH_r13. Two replicas of one model (identical
    weights, independent KV pools) behind a ``ServingRouter``; every
    timed replay is a seeded Poisson shared-prefix mixture, modes
    interleaved per rep, prefix caches evicted cold between replays.

    Leg A (routing): cache-aware vs round-robin placement on the SAME
    arrival stream, gating

      - computed prefill tokens: cache-aware <= 0.7x round-robin (the
        cluster pays each shared prefix ~once instead of once per replica),
      - goodput-under-SLO: cache-aware >= round-robin (medians over reps),
      - byte-equality: checked completed streams == direct single-frontend
        decode_pipeline runs of the same prompts,
      - zero engine compiles on EVERY replica during every timed replay.

    Leg B (disaggregation): 1 prefill + 1 decode replica vs the same two
    replicas colocated, same workload, gating >= 1 prefill->decode handoff
    per rep (KV byte-exactness is pinned below the router by
    tests/unit/test_serving_router.py and implied by the stream gate here)
    and decode TBT p95 <= the colocated leg's (medians over reps) — the
    interference-removal claim disaggregation exists for.

    Smoke: one rep each at tiny sizes, correctness gates only."""
    from deepspeed_tpu.inference.v2.serving import (PoissonLoadGen,
                                                    ServingCluster,
                                                    ServingRouter,
                                                    WorkloadComponent,
                                                    goodput_report, replay)
    classes = [{"name": "interactive", "priority": 2,
                "ttft_slo_ms": 4000.0, "tbt_slo_ms": 600.0},
               {"name": "batch", "priority": 0,
                "ttft_slo_ms": 60000.0, "tbt_slo_ms": 20000.0}]
    serving = {"classes": classes, "decode_slice": 4, "idle_wait_s": 0.002}
    engines = []
    for _ in range(2):
        # pool sized so CONCENTRATED caching fits (4 rows x 12 blocks live
        # + ~5 prefixes x 9 blocks cached) but caching every prefix on
        # every replica does NOT: round-robin duplicates all 8 prefixes per
        # replica (72 blocks) and pays evictions for it — the
        # cluster-cache-capacity half of the cache-aware argument
        e, vocab = build_frontend_engine(on_tpu, pool_blocks=112, ctx=192,
                                         prefix_cache=True)
        _force_paged(e)
        engines.append(e)
    if smoke:
        reps = 1
    ok = True
    results = {}

    def replay_once(router_cfg, roles, arrivals, duration):
        _clear_prefix_caches(engines)
        cluster = ServingCluster(engines, serving=serving, roles=roles)
        rt = ServingRouter(cluster, router_cfg)
        prefill0 = [e.scheduler.prefill_tokens_completed for e in engines]
        c0 = [e.compiles for e in engines]
        t0 = time.time()
        rt.start()
        handles = replay(rt, arrivals)
        rt.drain(timeout=3.0 * duration + 10.0)
        wall = time.time() - t0
        rt.close()           # past-deadline stragglers cancel: 0 goodput
        compiles = [e.compiles - c for e, c in zip(engines, c0)]
        prefill = sum(e.scheduler.prefill_tokens_completed - p
                      for e, p in zip(engines, prefill0))
        tbts = [g for h in handles if h.status == "finished"
                for g in h.tbt_ms]
        return {
            "handles": handles, "wall": wall, "compiles": compiles,
            "prefill_tokens": prefill,
            "tbt_p95_ms": (round(float(np.percentile(
                np.asarray(tbts, np.float64), 95)), 2) if tbts else None),
            "routed": dict(rt.stats.routed),
            "cache_hit_blocks": rt.stats.cache_hit_blocks,
            "rebalances": rt.stats.rebalances,
            "handoffs": rt.stats.handoffs,
            "handoff_bytes": rt.stats.handoff_bytes,
            "report": goodput_report(handles, wall),
        }

    # ---- leg A: cache-aware vs round-robin routing ------------------- #
    # 8 equal shared-prefix components: enough groups that hash affinity
    # spreads them across 2 replicas, so stickiness does not congest one
    # side. balance=16 lets a group SPILL once its sticky replica runs ~8
    # requests deeper than the other (the cold side then pays the prefix
    # once and the group balances warm-vs-warm) — the stickiness/balance
    # tradeoff the knob exists for.
    rate, duration = (8.0, 3.0) if smoke else (6.0, 9.0)
    mix = [WorkloadComponent("interactive" if i < 6 else "batch",
                             1.0, [4], [8, 16] if i < 6 else [24],
                             prefix_len=144) for i in range(8)]
    arrivals = PoissonLoadGen(rate=rate, mix=mix, vocab=vocab,
                              seed=seed).arrivals(duration=duration)
    policies = ["cache_aware"] if smoke else ["cache_aware", "round_robin"]
    routing = {p: [] for p in policies}
    # one untimed warm replay (a short slice of the stream): absorbs every
    # first-serving lazy cost so rep 0 measures what reps 1-2 measure
    warm = arrivals[:min(8, len(arrivals))]
    replay_once({"policy": "round_robin"}, ["serve", "serve"], warm, 1.0)
    for r in range(reps):
        for policy in policies:
            res = replay_once({"policy": policy, "balance": 16.0},
                              ["serve", "serve"], arrivals, duration)
            checked, equal = _check_router_streams(
                engines[0], res["handles"], 12 if smoke else 32, 170_000)
            a_checked, a_bad = _attribution_gate(res["handles"])
            out = {
                "leg": "router", "mode": policy, "rep": r, "rate": rate,
                "duration": duration, "arrivals": len(arrivals),
                "prefill_tokens": res["prefill_tokens"],
                "routed": res["routed"],
                "cache_hit_blocks": res["cache_hit_blocks"],
                "rebalances": res["rebalances"],
                "streams_checked": checked, "streams_equal": equal,
                "outputs_equal": equal == checked,
                "attribution_checked": a_checked,
                "attribution_bad": a_bad[:4],
                "attribution_ok": a_checked > 0 and not a_bad,
                "compiles_during_timed": res["compiles"],
                **res["report"],
            }
            routing[policy].append(out)
            print(json.dumps(out), flush=True)
            if not out["outputs_equal"] or any(c != 0 for c in
                                               res["compiles"]) \
                    or not out["attribution_ok"]:
                ok = False
    results["routing"] = routing

    # ---- leg B: disaggregated vs colocated --------------------------- #
    rate, duration = (5.0, 2.5) if smoke else (8.0, 6.0)
    mix = [WorkloadComponent("interactive", 3.0, [96], [12, 16]),
           WorkloadComponent("batch", 1.0, [96], [24])]
    arrivals = PoissonLoadGen(rate=rate, mix=mix, vocab=vocab,
                              seed=seed + 1).arrivals(duration=duration)
    topos = {"disaggregated": (["prefill", "decode"],
                               {"topology": "disaggregated"}),
             "colocated": (["serve", "serve"],
                           {"policy": "round_robin"})}
    disagg = {t: [] for t in topos}
    for r in range(reps):
        for topo, (roles, cfg) in topos.items():
            res = replay_once(cfg, roles, arrivals, duration)
            # the decode engine under disaggregation is engines[1]; direct
            # references run there so prefill+decode share one engine
            checked, equal = _check_router_streams(
                engines[1], res["handles"], 8 if smoke else 24, 180_000)
            a_checked, a_bad = _attribution_gate(res["handles"])
            out = {
                "leg": "router_disagg", "mode": topo, "rep": r,
                "rate": rate, "duration": duration,
                "arrivals": len(arrivals),
                "handoffs": res["handoffs"],
                "handoff_bytes": res["handoff_bytes"],
                "tbt_p95_ms": res["tbt_p95_ms"],
                "streams_checked": checked, "streams_equal": equal,
                "outputs_equal": equal == checked,
                "attribution_checked": a_checked,
                "attribution_bad": a_bad[:4],
                "attribution_ok": a_checked > 0 and not a_bad,
                "compiles_during_timed": res["compiles"],
                **res["report"],
            }
            disagg[topo].append(out)
            print(json.dumps(out), flush=True)
            if not out["outputs_equal"] or any(c != 0 for c in
                                               res["compiles"]) \
                    or not out["attribution_ok"]:
                ok = False
            if topo == "disaggregated" and res["handoffs"] < 1:
                ok = False
    results["disagg"] = disagg

    for e in engines:
        _unforce_paged(e)

    # ---- gates -------------------------------------------------------- #
    if not smoke:
        med_prefill = {p: float(np.median([x["prefill_tokens"]
                                           for x in routing[p]]))
                       for p in policies}
        med_goodput = {p: float(np.median([x["goodput_tokens_per_sec"]
                                           for x in routing[p]]))
                       for p in policies}
        reduction = 1.0 - med_prefill["cache_aware"] \
            / max(1.0, med_prefill["round_robin"])
        gate_prefill = reduction >= 0.30
        gate_goodput = (med_goodput["cache_aware"]
                        >= med_goodput["round_robin"])
        print(json.dumps({"gate": "cache_aware_prefill_reduction",
                          "ok": bool(gate_prefill),
                          "reduction": round(reduction, 3),
                          "median_prefill_tokens": med_prefill,
                          "bar": 0.30}), flush=True)
        print(json.dumps({"gate": "cache_aware_goodput",
                          "ok": bool(gate_goodput),
                          "median_goodput": med_goodput}), flush=True)
        med_tbt = {t: float(np.median([x["tbt_p95_ms"] for x in disagg[t]
                                       if x["tbt_p95_ms"] is not None]))
                   for t in topos}
        gate_tbt = med_tbt["disaggregated"] <= med_tbt["colocated"]
        print(json.dumps({"gate": "disagg_decode_tbt",
                          "ok": bool(gate_tbt),
                          "median_tbt_p95_ms": med_tbt}), flush=True)
        ok = ok and gate_prefill and gate_goodput and gate_tbt
    handoff_reps = [x["handoffs"] for x in disagg["disaggregated"]]
    print(json.dumps({"gate": "prefill_decode_handoff",
                      "ok": all(h >= 1 for h in handoff_reps),
                      "handoffs_per_rep": handoff_reps}), flush=True)
    return ok


def _check_chaos_streams(engine, handles, limit, uid_base):
    """Byte-equality under chaos: finished streams (MIGRATED ones first —
    they are the point) vs direct decode_pipeline runs of the same prompts
    on a forced-paged engine. Returns (checked, equal, migrated_checked)."""
    finished = [h for h in handles if h.status == "finished"]
    finished.sort(key=lambda h: -h.migrated)
    check = finished[:limit]
    equal = migrated = 0
    for i, h in enumerate(check):
        uid = uid_base + i
        engine._put_nofetch([uid], [h.prompt])
        out = engine.decode_pipeline([uid]).run(len(h.tokens))
        engine.flush([uid])
        if [int(t) for t in out[0]] == h.tokens:
            equal += 1
            migrated += bool(h.migrated)
    return len(check), equal, migrated


def locksan_gate(leg: str) -> bool:
    """Runtime lock-order gate for legs run under ``DSTPU_LOCKSAN=1``
    (docs/THREADLINT.md): ZERO observed acquisition cycles, and every edge
    the sanitizer recorded must be predicted by threadlint's static lock
    graph (static >= observed — the analyzer is never blind to an ordering
    the runtime actually took). No-op (and passing) when the sanitizer is
    not armed, so the legs behave identically outside the smoke harness.
    Blocking-under-lock events are REPORTED but don't flip the gate — the
    static rule (TL002) owns that class, with annotations for the
    deliberate handoffs."""
    from deepspeed_tpu.utils import locksan
    if not locksan.enabled():
        return True
    from deepspeed_tpu.tools.threadlint.config import (ThreadLintConfig,
                                                       find_config)
    from deepspeed_tpu.tools.threadlint.model import static_lock_graph
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cfg_path = find_config(root)
    config = ThreadLintConfig.load(cfg_path) if cfg_path         else ThreadLintConfig()
    static = set(static_lock_graph([os.path.join(root, "deepspeed_tpu")],
                                   config))
    rep = locksan.report()
    unexplained = sorted(locksan.check_static(static))
    out = {"locksan_leg": leg,
           "observed_edges": sorted(locksan.edges()),
           "cycles": rep["cycles"],
           "unexplained_edges": unexplained,
           "blocking_under_lock": rep["blocking"]}
    print(json.dumps(out), flush=True)
    return not rep["cycles"] and not unexplained


def run_chaos(on_tpu: bool, smoke: bool, seed: int = 0, reps: int = 3):
    """The fault-tolerance leg (docs/SERVING.md "Failure semantics"),
    BENCH_r14: N colocated replicas behind a health-monitored
    ``ServingRouter`` replay a seeded Poisson workload while fault
    injection KILLS one replica's serving loop (``serve.engine_step.<r>``
    action=raise) and STALLS another's (action=stall past the down
    deadline) mid-run. The monitor detects (liveness + progress-stall),
    fences, migrates every in-flight stream, and auto-rejoins each replica
    once its thread exits — re-warming off the hot path.

    Gates, every rep:

      - every checked non-shed stream byte-identical to an uninterrupted
        direct decode_pipeline reference (forced-paged kernel discipline on
        every engine AND the references, so migration re-prefill is
        bit-equal — the gate tests exactly what failover changes: WHERE
        the stream ran);
      - both injected faults fired AND were detected (>=1 liveness down,
        >=1 stall down), >=1 request migrated, the faulted replicas
        rejoined and ended HEALTHY;
      - ZERO engine compiles on every replica across the chaos replay —
        including each rejoin's re-warm;
      - allocator free blocks back to baseline on every replica after the
        replay (survivors AND rejoined corpses);
      - with ``DSTPU_TRACE`` set, the injected raise leaves a
        flight-recorder crash dump (``trace_check --expect-crash`` in
        bench_smoke validates it).

    Full runs additionally gate goodput-under-SLO against an N-1-replica
    NO-FAULT floor replayed on the same engines (median over reps):
    losing-then-healing one replica must degrade gracefully toward the
    floor, not collapse. Smoke: 2 replicas, one kill + one stall, one rep,
    correctness gates only (<60 s warm)."""
    from deepspeed_tpu.inference.v2.serving import (PoissonLoadGen,
                                                    ServingCluster,
                                                    ServingRouter,
                                                    WorkloadComponent,
                                                    goodput_report, replay)
    from deepspeed_tpu.utils import fault_injection as fi
    n_replicas = 2 if smoke else 3
    engines = []
    for _ in range(n_replicas):
        e, vocab = build_frontend_engine(on_tpu, pool_blocks=20, ctx=192)
        _force_paged(e)
        engines.append(e)
    health = {"enabled": True, "interval_s": 0.02,
              "suspect_after_s": 0.4, "down_after_s": 1.0,
              "fence_join_s": 0.5, "auto_rejoin": True}
    # SLOs sized to this box's detection + migration window (the
    # tight-interactive triage regime is the --frontend leg's subject;
    # here goodput must track CAPACITY so the N-1 floor comparison
    # measures graceful degradation, not SLO-accounting artifacts)
    classes = [{"name": "interactive", "priority": 2,
                "ttft_slo_ms": 5000.0, "tbt_slo_ms": 1500.0},
               {"name": "batch", "priority": 0,
                "ttft_slo_ms": 60000.0, "tbt_slo_ms": 20000.0}]
    serving = {"classes": classes, "decode_slice": 4,
               "idle_wait_s": 0.002}
    rate, duration = (8.0, 3.5) if smoke else (20.0, 12.0)
    mix = [WorkloadComponent("interactive", 4.0, [16, 32], [8, 16, 24]),
           WorkloadComponent("batch", 1.0, [48], [64])]
    arrivals = PoissonLoadGen(rate=rate, mix=mix, vocab=vocab,
                              seed=seed).arrivals(duration=duration)
    if smoke:
        reps = 1
    # one kill + one stall, aimed at distinct replicas mid-run; `at` counts
    # the TARGET replica's own loop iterations (replica-scoped sites), so
    # both fire early enough to leave room for detection + rejoin
    stall_s = 1.5 if smoke else 2.0
    plan = (f"serve.engine_step.r0:at=25:action=raise;"
            f"serve.engine_step.r1:at=60:action=stall:delay_s={stall_s}")

    def replay_once(engine_set, faults):
        frees = [e.free_blocks for e in engine_set]
        cluster = ServingCluster(engine_set, serving=serving)
        rt = ServingRouter(cluster, {"policy": "round_robin",
                                     "health": health})
        c0 = [e.compiles for e in engine_set]
        if faults:
            fi.install(fi.parse_plan(faults, seed=seed))
        try:
            t0 = time.time()
            rt.start()
            handles = replay(rt, arrivals)
            rt.drain(timeout=3.0 * duration + 20.0)
            rt.health.wait_all_healthy(30.0)
            wall = time.time() - t0
            fired = list(fi.active().fired) if faults else []
        finally:
            fi.clear()
        hs = rt.health.stats
        rt.close()           # past-deadline stragglers cancel: 0 goodput
        return {
            "handles": handles, "wall": wall, "fired": fired,
            "compiles": [e.compiles - c for e, c in zip(engine_set, c0)],
            "free_ok": [e.free_blocks == f
                        for e, f in zip(engine_set, frees)],
            "health": hs, "all_healthy": rt.health.all_healthy(),
            "report": goodput_report(handles, wall),
        }

    # untimed warm replay: absorbs every first-serving lazy cost so the
    # zero-compile gate tests the chaos machinery, not cold starts
    replay_once(engines, None)

    ok = True
    chaos_reps, floor_reps = [], []
    trace_dir = os.environ.get("DSTPU_TRACE", "")
    for r in range(reps):
        res = replay_once(engines, plan)
        hs = res["health"]
        checked, equal, migrated_checked = _check_chaos_streams(
            engines[-1], res["handles"], 16 if smoke else 40, 200_000)
        a_checked, a_bad = _attribution_gate(res["handles"])
        m_total, m_ok, m_bad = _migrated_chain_gate(res["handles"])
        crash_dump = (os.path.exists(os.path.join(
            trace_dir, "trace_crash.json")) if trace_dir else None)
        out = {
            "leg": "chaos", "rep": r, "replicas": n_replicas,
            "rate": rate, "duration": duration, "arrivals": len(arrivals),
            "faults_fired": [f"{site}@{hit}:{act}"
                             for site, hit, act in res["fired"]],
            "liveness_downs": hs.liveness_downs,
            "stall_downs": hs.stall_downs,
            "migrations": hs.migrations,
            "salvaged": hs.salvaged,
            "reprefilled": hs.reprefilled,
            "migration_sheds": hs.migration_sheds,
            "rejoins": hs.rejoins,
            "detect_p95_ms": (round(float(np.percentile(
                np.asarray(hs.detect_ms, np.float64), 95)), 1)
                if hs.detect_ms else None),
            "all_healthy_after": res["all_healthy"],
            "streams_checked": checked, "streams_equal": equal,
            "migrated_streams_checked": migrated_checked,
            "outputs_equal": equal == checked,
            "attribution_checked": a_checked,
            "attribution_bad": a_bad[:4],
            "attribution_ok": a_checked > 0 and not a_bad,
            "migrated_finished": m_total,
            "migrated_chains_ok": m_ok,
            "migrated_chains_bad": m_bad[:8],
            "compiles_during_timed": res["compiles"],
            "allocator_at_baseline": res["free_ok"],
            "flight_recorder_dump": crash_dump,
            **res["report"],
        }
        chaos_reps.append(out)
        print(json.dumps(out), flush=True)
        if not out["outputs_equal"] or any(c != 0 for c in res["compiles"]) \
                or not all(res["free_ok"]) or not res["all_healthy"] \
                or hs.liveness_downs < 1 or hs.stall_downs < 1 \
                or hs.migrations < 1 or hs.rejoins < 2 \
                or not out["attribution_ok"] or m_ok < m_total:
            ok = False
        if crash_dump is False:
            ok = False
        if not smoke:
            floor = replay_once(engines[:-1], None)
            fout = {"leg": "chaos_floor", "rep": r,
                    "replicas": n_replicas - 1,
                    "compiles_during_timed": floor["compiles"],
                    **floor["report"]}
            floor_reps.append(fout)
            print(json.dumps(fout), flush=True)
            if any(c != 0 for c in floor["compiles"]):
                ok = False
    if not smoke:
        med_chaos = float(np.median([x["goodput_tokens_per_sec"]
                                     for x in chaos_reps]))
        med_floor = float(np.median([x["goodput_tokens_per_sec"]
                                     for x in floor_reps]))
        gate = med_chaos >= 0.7 * med_floor and med_chaos > 0
        print(json.dumps({"gate": "chaos_goodput_floor", "ok": bool(gate),
                          "median_goodput_chaos": med_chaos,
                          "median_goodput_n_minus_1_floor": med_floor,
                          "bar": "chaos >= 0.7 x floor"}), flush=True)
        ok = ok and gate
    return ok


def run_serving_trace_overhead(on_tpu: bool, smoke: bool, seed: int = 0,
                               reps: int = 5):
    """Serving-side tracer/attribution overhead leg (the
    ``train_bench.py --trace-overhead`` discipline applied to the router
    stack), BENCH_r16. The SAME seeded burst workload (every arrival
    submitted immediately — the wall time is serving work, not open-loop
    sleeps) replays against a 2-replica cache-aware router with flow
    tracing + phase attribution ON vs OFF, orders ALTERNATED per rep.

    Gates, every rep:

      - byte-identical streams: each request finished on both sides
        produced the same tokens (tracing/attribution must not perturb
        placement-independent greedy serving);
      - zero engine compiles on every replica in every timed replay;
      - attribution consistency on the ON side (ledger sums to the
        client-measured latency per finished request).

    Full runs additionally gate: median per-rep wall ratio (ON/OFF)
    <= 1.02 — flow tracing plus the ledger costs at most 2% of serving
    wall. Smoke: one rep, correctness gates only."""
    from deepspeed_tpu.inference.v2.serving import (PoissonLoadGen,
                                                    ServingCluster,
                                                    ServingRouter,
                                                    WorkloadComponent,
                                                    replay)
    from deepspeed_tpu.monitor.trace import tracer as _tr
    classes = [{"name": "interactive", "priority": 2,
                "ttft_slo_ms": 60000.0, "tbt_slo_ms": 20000.0},
               {"name": "batch", "priority": 0,
                "ttft_slo_ms": 60000.0, "tbt_slo_ms": 20000.0}]
    engines = []
    for _ in range(2):
        e, vocab = build_frontend_engine(on_tpu, pool_blocks=112, ctx=192,
                                         prefix_cache=True)
        _force_paged(e)
        engines.append(e)
    n_arrivals = 16 if smoke else 48
    mix = [WorkloadComponent("interactive", 3.0, [16, 32], [8, 16],
                             prefix_len=64),
           WorkloadComponent("batch", 1.0, [32], [24])]
    arrivals = PoissonLoadGen(rate=8.0, mix=mix, vocab=vocab,
                              seed=seed).arrivals(n=n_arrivals)
    if smoke:
        reps = 1

    def replay_once(attribution: bool):
        _clear_prefix_caches(engines)
        serving = {"classes": classes, "decode_slice": 4,
                   "idle_wait_s": 0.002, "attribution": attribution}
        cluster = ServingCluster(engines, serving=serving)
        rt = ServingRouter(cluster, {"policy": "cache_aware",
                                     "balance": 16.0})
        c0 = [e.compiles for e in engines]
        t0 = time.perf_counter()
        rt.start()
        handles = replay(rt, arrivals, speed=1e9)   # burst: no pacing sleeps
        rt.drain(timeout=120.0)
        wall = time.perf_counter() - t0
        rt.close()
        return {"handles": handles, "wall": wall,
                "compiles": [e.compiles - c for e, c in zip(engines, c0)]}

    was_enabled = _tr.enabled        # $DSTPU_TRACE may have armed it
    _tr.enabled = False
    replay_once(False)               # untimed warm: lazy costs absorbed
    ok = True
    ratios = []
    reps_out = []
    for r in range(reps):
        order = ("on", "off") if r % 2 == 0 else ("off", "on")
        res = {}
        for side in order:
            if side == "on":
                _tr.configure(enabled=True)
            else:
                _tr.enabled = False
            res[side] = replay_once(attribution=(side == "on"))
            _tr.enabled = False
        checked = equal = 0
        for a, b in zip(res["on"]["handles"], res["off"]["handles"]):
            if a.status == "finished" and b.status == "finished":
                checked += 1
                equal += a.tokens == b.tokens
        a_checked, a_bad = _attribution_gate(res["on"]["handles"])
        ratio = res["on"]["wall"] / res["off"]["wall"]
        ratios.append(ratio)
        out = {
            "leg": "serving_trace_overhead", "rep": r, "order": list(order),
            "arrivals": len(arrivals),
            "wall_on_s": round(res["on"]["wall"], 4),
            "wall_off_s": round(res["off"]["wall"], 4),
            "ratio": round(ratio, 4),
            "streams_checked": checked, "streams_equal": equal,
            "outputs_equal": checked == equal and checked >= int(
                0.9 * len(arrivals)),
            "attribution_checked": a_checked,
            "attribution_ok": a_checked > 0 and not a_bad,
            "compiles_during_timed": [res[s]["compiles"] for s in order],
        }
        reps_out.append(out)
        print(json.dumps(out), flush=True)
        if not out["outputs_equal"] or not out["attribution_ok"] \
                or any(c != 0 for side in ("on", "off")
                       for c in res[side]["compiles"]):
            ok = False
    _tr.enabled = was_enabled
    for e in engines:
        _unforce_paged(e)
    med = float(np.median(ratios))
    gate = {"gate": "serving_trace_overhead",
            "median_ratio": round(med, 4), "ratios_per_rep":
            [round(x, 4) for x in ratios], "bar": 1.02,
            "enforced": not smoke,
            "ok": bool(smoke or med <= 1.02)}
    print(json.dumps(gate), flush=True)
    if not smoke:
        ok = ok and med <= 1.02
    return ok


def _splitk_op_microbench(on_tpu: bool, splits: int, iters: int = 30):
    """Op-level split-K point: the paged decode attention op alone, split=1
    vs split=S, on the path this box actually runs (TPU: Pallas kernel;
    CPU: the page-granular XLA scan — split=1 walks all NC pages
    sequentially, split=S walks ceil(NC/S) wider steps, so the win is the
    scan-iteration overhead the splits amortise). Small batch x long ctx x
    the bench model's head_dim — the regime the engine leg serves."""
    import jax
    import jax.numpy as jnp
    from functools import partial
    from deepspeed_tpu.ops.pallas.paged_splitk import (
        paged_decode_attention_xla)
    S, H, HKV, D, bs, NC = 4, 4, 2, 16, 16, 64     # ctx 1024/seq
    rng = np.random.RandomState(0)
    kv = jnp.asarray(rng.randn(S * NC + 1, 2, HKV, bs, D)
                     .astype(np.float32))
    q = jnp.asarray(rng.randn(S, H, D).astype(np.float32))
    bt = jnp.asarray(np.arange(S * NC).reshape(S, NC) + 1, jnp.int32)
    ctx = jnp.full((S,), NC * bs, jnp.int32)

    def timed(n_splits):
        # the XLA fallback at both points: the ONLY difference between the
        # legs is the split count, so the ratio is pure split-K (comparing
        # against the chunk-serial Pallas kernel here would conflate the
        # win with CPU interpret-mode overhead)
        f = jax.jit(partial(paged_decode_attention_xla,
                            n_splits=n_splits))
        f(q, kv, bt, ctx).block_until_ready()      # compile outside timing
        t0 = time.perf_counter()
        for _ in range(iters):
            out = f(q, kv, bt, ctx)
        out.block_until_ready()
        return (time.perf_counter() - t0) / iters

    t1, ts = timed(1), timed(splits)
    return {"op_ctx": NC * bs, "op_seqs": S, "op_head_dim": D,
            "op_split1_us": round(1e6 * t1, 1),
            "op_splitS_us": round(1e6 * ts, 1),
            "op_speedup": round(t1 / ts, 2)}


def run_long_context(on_tpu: bool, smoke: bool, seqs=None, prompt=None,
                     gen=None, splits: int = 4, reps: int = 3):
    """Flash-decoding long-context leg (docs/SERVING.md "Attention
    kernels"), BENCH_r17: few sequences x long context — the split-K
    regime, where grid parallelism over sequences alone leaves the chip
    (or, on CPU, the scan) serial over each row's pages. ONE warmed engine
    with the pow2 split ladder ``[1..splits]`` serves the same seeded
    prompts through the DecodePipeline twice per rep: pinned to the
    chunk-serial split=1 program (``attn_rung_override``) and under auto
    rung selection (climbs the ladder as live ctx crosses
    ``min_ctx_per_split`` multiples).

    Gates: (a) token streams IDENTICAL between split=1 and the ladder —
    same forward math, different grid decomposition (the op-level LSE-merge
    equality tests put the two paths within float rtol; greedy argmax over
    the bench model's logits is byte-stable across that); (b) zero timed
    compiles — every rung program came out of warmup(); (c) allocator back
    to baseline each rep; (d) the auto leg actually climbed the ladder
    (merged_steps > 0; otherwise the comparison is vacuous); (e) full runs
    only: the op-level point shows >= 1.3x split=S over split=1 on the
    measurable fallback path (CPU box: the XLA scan)."""
    seqs = seqs if seqs is not None else (2 if smoke else 3)
    prompt = prompt if prompt is not None else (96 if smoke else 384)
    gen = gen if gen is not None else (8 if smoke else 32)
    min_ctx = 16 if smoke else 64
    reps = 1 if smoke else reps
    engine, vocab = build_engine(
        on_tpu, seqs=seqs, prompt=prompt, gen=gen,
        warmup=True, warmup_bursts=False,
        extra_config={
            # small pages: the long ctx becomes MANY pages per row, the
            # regime where chunk-serial decode is scan-bound
            "kv_cache": {"block_size": 16},
            "attention": {"decode_splits": splits,
                          "min_ctx_per_split": min_ctx}})
    _force_paged(engine)
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, vocab, size=(prompt,)).astype(np.int32)
               for _ in range(seqs)]
    uid_base = [60_000]

    def serve(rung):
        """One timed decode run at a pinned rung (None = auto ladder)."""
        engine.attn_rung_override = rung
        uid_base[0] += seqs
        uids = list(range(uid_base[0], uid_base[0] + seqs))
        engine._put_nofetch(uids, prompts)
        pipe = engine.decode_pipeline(uids)
        t0 = time.time()
        out = pipe.run(gen)
        wall = time.time() - t0
        engine.flush(uids)
        engine.attn_rung_override = None
        return [list(map(int, row)) for row in out], wall

    # untimed: compile-free from here (warmup covered every rung)
    serve(1)
    serve(None)
    free0 = engine.free_blocks
    c0 = engine.compiles
    ok = True
    ladder = engine.attn_split_ladder
    for rep in range(reps):
        ref, wall1 = serve(1)
        engine.attn_stats.reset()
        got, walls = serve(None)
        s = engine.attn_stats
        out = {
            "leg": "long_context", "rep": rep, "seqs": seqs,
            "prompt": prompt, "gen": gen, "ladder": ladder,
            "min_ctx_per_split": min_ctx,
            "split1_tok_s": round(seqs * gen / wall1, 1),
            "ladder_tok_s": round(seqs * gen / walls, 1),
            "engine_speedup": round(wall1 / walls, 2),
            "outputs_equal": got == ref,
            "ladder_engaged": s.merged_steps > 0,
            "splits_per_select": round(s.splits_per_select, 2),
            "max_live_ctx": s.max_live_ctx,
            "compiles_during_timed_runs": engine.compiles - c0,
            "allocator_at_baseline": engine.free_blocks == free0,
        }
        print(json.dumps(out), flush=True)
        ok = ok and out["outputs_equal"] and out["ladder_engaged"] \
            and out["compiles_during_timed_runs"] == 0 \
            and out["allocator_at_baseline"]
    op = _splitk_op_microbench(on_tpu, splits,
                               iters=(10 if smoke else 30))
    gate_op = smoke or op["op_speedup"] >= 1.3
    print(json.dumps({"gate": "splitk_long_context",
                      "ok": bool(ok and gate_op), **op}), flush=True)
    return bool(ok and gate_op)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seqs", type=int, default=None,
                    help="concurrent sequences (default: 32; --spec leg: 4)")
    ap.add_argument("--prompt", type=int, default=None,
                    help="prompt tokens (default: 128; --spec leg: 48)")
    ap.add_argument("--gen", type=int, default=None,
                    help="greedy tokens per sequence (default: 64; the "
                         "--spec leg defaults to 128 so the loop regime "
                         "n-gram drafting rides can establish)")
    ap.add_argument("--rates", default="2,6")
    ap.add_argument("--duration", type=float, default=20.0)
    ap.add_argument("--int8", action="store_true",
                    help="weight-only int8 serving (quantization.weight_bits=8)")
    ap.add_argument("--modes", default="burst",
                    help="comma list of 'burst' (fused decode bursts) and/or "
                         "'mixed' (SplitFuse chunk+decode composition "
                         "through scheduler passes)")
    ap.add_argument("--burst", type=int, default=16,
                    help="fused decode tokens per host round trip (measured "
                         "v5e-1 tunnel saturation: burst 8 -> 3.6k total "
                         "tok/s, burst 16 -> 8.5k; bigger bursts trade "
                         "admission latency for RTT amortisation)")
    ap.add_argument("--shared-prefix", action="store_true",
                    help="run the shared-prefix (prefix-cache) leg instead of "
                         "the load sweep: N requests sharing a long system "
                         "prompt, cache-on vs cache-off")
    ap.add_argument("--steady-state", action="store_true",
                    help="run the steady-state decode leg instead of the load "
                         "sweep: a fixed decode set through the pre-pipeline "
                         "per-token loop vs the async double-buffered "
                         "DecodePipeline, with a byte-identical-greedy gate")
    ap.add_argument("--frontend", action="store_true",
                    help="run the SLO-aware frontend leg: a seeded Poisson "
                         "mixed-priority workload against each preemption "
                         "policy (offload / recompute / reject-only) on one "
                         "warmed engine, gating byte-equality, zero timed "
                         "compiles and goodput-under-SLO")
    ap.add_argument("--router", action="store_true",
                    help="run the multi-replica router leg: 2 replicas "
                         "behind a ServingRouter on seeded shared-prefix "
                         "Poisson traffic — cache-aware vs round-robin "
                         "routing (prefill-token reduction + goodput), "
                         "disaggregated vs colocated prefill/decode "
                         "(handoffs + decode TBT), gating stream "
                         "byte-equality vs direct single-frontend runs and "
                         "zero steady-state compiles per replica")
    ap.add_argument("--chaos", action="store_true",
                    help="run the fault-tolerance leg: N replicas behind a "
                         "health-monitored router replay a seeded Poisson "
                         "workload while injected faults kill one serving "
                         "loop and stall another — gating byte-identical "
                         "non-shed streams vs uninterrupted references, "
                         "detection of both failure modes, zero compiles "
                         "incl. rejoin re-warm, allocator baseline on every "
                         "replica, and (full) goodput >= 0.7x an "
                         "N-1-replica no-fault floor")
    ap.add_argument("--lora", action="store_true",
                    help="run the multi-tenant LoRA leg: a seeded Poisson "
                         "mix drawing tenants from more registered adapters "
                         "than the adapter pool holds, served through the "
                         "grouped LoRA decode matmul — gating byte-identical "
                         "streams vs direct per-adapter runs, zero timed "
                         "compiles across adapter churn, allocator + adapter "
                         "pool at baseline every rep, and (full) goodput >= "
                         "1.5x a naive one-adapter-at-a-time baseline")
    ap.add_argument("--trace-overhead", action="store_true",
                    help="run the serving tracer/attribution overhead leg: "
                         "the same seeded burst router workload with flow "
                         "tracing + phase attribution ON vs OFF (orders "
                         "alternated per rep), gating byte-identical "
                         "streams, zero timed compiles, attribution "
                         "consistency, and (full) median overhead <= 2%")
    ap.add_argument("--spec", action="store_true",
                    help="run the speculative-decoding leg: spec-off "
                         "DecodePipeline vs draft-and-verify "
                         "SpecDecodePipeline on one warmed engine over "
                         "repetitive-text and natural-text workloads, "
                         "gating byte-identical greedy streams, zero timed "
                         "compiles across the (bucket, k) grid, allocator "
                         "baseline after reject-heavy runs, and the "
                         "repetitive-leg tok/s ratio")
    ap.add_argument("--long-context", action="store_true",
                    help="run the flash-decoding long-context leg: few "
                         "sequences x long ctx on ONE warmed engine with "
                         "the pow2 split ladder — split=1 (chunk-serial) "
                         "vs auto rung selection, gating identical token "
                         "streams, zero timed compiles, allocator "
                         "baseline, ladder engagement, and (full) the "
                         "op-level split-K point >= 1.3x on the "
                         "measurable fallback path (BENCH_r17)")
    ap.add_argument("--splits", type=int, default=4,
                    help="long-context leg: top rung of the pow2 split "
                         "ladder")
    ap.add_argument("--spec-k", type=int, default=15,
                    help="spec leg: max draft tokens per verify step (the "
                         "ladder dispatches pow2-minus-1 rungs up to it; "
                         "k+1 a power of two keeps the chunk kernel's "
                         "q-block whole)")
    ap.add_argument("--kv-dtype", default=None, choices=["int8"],
                    help="with --frontend: run the quantized-KV leg instead "
                         "— the same seeded Poisson workload against an "
                         "fp (bf16/f32) pool and an int8 pool sized from "
                         "ONE byte budget, both with prefix cache AND spec "
                         "decode on, gating byte-identical quantized "
                         "streams across cache/spec/preempt paths, zero "
                         "timed compiles, the bytes/token drop, and "
                         "goodput-under-SLO int8 >= fp (docs/SERVING.md "
                         "'Quantized KV')")
    ap.add_argument("--smoke", action="store_true",
                    help="frontend/spec legs: tiny sizes, correctness "
                         "gates only (<60 s; no throughput comparison)")
    ap.add_argument("--rate", type=float, default=None,
                    help="frontend leg: Poisson arrivals/sec (default: an "
                         "oversubscribing 36/s full, 10/s smoke)")
    ap.add_argument("--reps", type=int, default=None,
                    help="replays per mode/rep count (default: 3; the "
                         "trace-overhead leg defaults to 5, its smoke to 1)")
    ap.add_argument("--requests", type=int, default=16,
                    help="shared-prefix leg: number of requests")
    ap.add_argument("--prefix", type=int, default=256,
                    help="shared-prefix leg: shared system-prompt tokens")
    ap.add_argument("--tail", type=int, default=32,
                    help="shared-prefix leg: unique tail tokens per request")
    args = ap.parse_args()

    import jax
    on_tpu = jax.default_backend() not in ("cpu",)
    from deepspeed_tpu.utils.compile_cache import setup_compile_cache
    setup_compile_cache(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    # one shared default for every leg's rep count; the trace-overhead
    # leg overrides to its own 5-rep default below
    reps = args.reps if args.reps is not None else 3
    if args.spec:
        ok = run_spec(on_tpu, args.smoke, k=args.spec_k,
                      seqs=args.seqs if args.seqs is not None else 4,
                      prompt=args.prompt if args.prompt is not None else 48,
                      gen=args.gen if args.gen is not None else 128,
                      reps=reps)
        sys.exit(0 if ok else 1)
    if args.long_context:
        ok = run_long_context(on_tpu, args.smoke, seqs=args.seqs,
                              prompt=args.prompt, gen=args.gen,
                              splits=args.splits, reps=reps)
        sys.exit(0 if ok else 1)
    if args.gen is None:
        args.gen = 64
    if args.seqs is None:
        args.seqs = 32
    if args.prompt is None:
        args.prompt = 128
    if args.trace_overhead:
        ok = run_serving_trace_overhead(
            on_tpu, args.smoke,
            reps=args.reps if args.reps is not None else 5)
        sys.exit(0 if ok else 1)
    if args.lora:
        rate = args.rate or (8.0 if args.smoke else 16.0)
        dur = 3.0 if args.smoke else min(args.duration, 10.0)
        ok = run_lora(on_tpu, args.smoke, rate=rate, duration=dur, reps=reps)
        sys.exit(0 if ok else 1)
    if args.chaos:
        ok = run_chaos(on_tpu, args.smoke, reps=reps)
        ok = locksan_gate("chaos") and ok
        sys.exit(0 if ok else 1)
    if args.router:
        ok = run_router(on_tpu, args.smoke, reps=reps)
        ok = locksan_gate("router") and ok
        sys.exit(0 if ok else 1)
    if args.frontend:
        if args.kv_dtype == "int8":
            rate = args.rate or (8.0 if args.smoke else 14.0)
            dur = 3.0 if args.smoke else min(args.duration, 8.0)
            ok = run_kv_dtype(on_tpu, args.smoke, rate=rate, duration=dur,
                              reps=reps)
            sys.exit(0 if ok else 1)
        rate = args.rate or (10.0 if args.smoke else 36.0)
        dur = 4.0 if args.smoke else min(args.duration, 15.0)
        ok = run_frontend(on_tpu, args.smoke, rate=rate, duration=dur,
                          reps=reps)
        sys.exit(0 if ok else 1)
    if args.shared_prefix:
        out = run_shared_prefix(on_tpu, args.requests, args.prefix, args.tail,
                                gen=min(args.gen, 16))
        print(json.dumps(out), flush=True)
        if not out["outputs_equal"]:
            # the leg's correctness gate: cached-KV reuse must not change
            # greedy outputs — a divergence means corrupted page adoption
            sys.exit(1)
        return
    if args.steady_state:
        out = run_steady_state(on_tpu, args.seqs, args.prompt, args.gen)
        print(json.dumps(out), flush=True)
        if (not out["outputs_equal"] or not out["fetch_is_token_row"]
                or out["compiles_during_timed_runs"] != 0):
            # gates: pipelined orchestration must not change greedy outputs,
            # the per-step transfer must stay one token row, and warm in-grid
            # serving must never compile (a bucket-keying regression shows
            # up here before it shows up as a throughput mystery)
            sys.exit(1)
        return
    engine, vocab = build_engine(on_tpu, args.seqs, args.prompt, args.gen,
                                 burst=args.burst, int8=args.int8)
    rng = np.random.RandomState(0)
    # warm run compiles every pass shape (prefill, mixed, fused burst)
    run_load_point(engine, vocab, rate=50.0, seqs=args.seqs,
                   prompt=args.prompt, gen=max(8, args.gen // 4),
                   duration=8.0 if on_tpu else 2.0, rng=rng, burst=args.burst)
    modes = args.modes.split(",")
    bad = [m for m in modes if m not in ("burst", "mixed")]
    if bad:
        ap.error(f"unknown --modes entries {bad}; valid: burst, mixed")
    for rate in [float(r) for r in args.rates.split(",")]:
        for mode in modes:
            out = run_load_point(engine, vocab, rate, args.seqs, args.prompt,
                                 args.gen, args.duration, rng,
                                 burst=args.burst, mode=mode)
            print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
