"""Collectives micro-benchmark over the device mesh.

Parity role: the reference's communication benchmark suite
(``benchmarks/README.md`` -> DeepSpeedExamples ``benchmarks/communication``:
all_reduce/all_gather/all_to_all/pt2pt sweeps printing algbw/busbw).  Here the
same sweep drives this framework's collectives API (``deepspeed_tpu.comm``)
over whatever mesh is available — N virtual CPU devices
(``--xla_force_host_platform_device_count``), one real chip (degenerate), or a
real slice — and prints one JSON line per (op, size).

Bus bandwidth uses the standard ring-algorithm correction factors the
reference's ``utils.calc_bw`` applies: allreduce 2(n-1)/n, allgather /
reducescatter (n-1)/n, alltoall (n-1)/n.

``--overlap`` runs the collective-overlap leg instead of the sweep: the same
bucketed all-gather issued (a) serially — each gather tied behind the previous
round's compute — and (b) pipelined one round ahead, the two-sided
tie-barrier/pin structure of the ZeRO-3 collective schedule
(``runtime/zero/prefetch.py``). Both programs carry in-jit
``jax.debug.callback`` stamps; the overlap fraction is measured from the
resulting gather/compute trace spans, not inferred from wall-clock deltas.
On a serial executor (1-core forced-host CPU) "overlap" is time-sliced window
interleaving — the schedule is still visible in the spans; wall-clock gains
need hardware that runs collectives async.

Usage: ``python benchmarks/comm_bench.py [--sizes-mb 1,4,16,64] [--trials 20]``
       ``python benchmarks/comm_bench.py --overlap [--sizes-mb 4] [--rounds 8]``
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax

# honour an explicit CPU request even when a site config pins the platform
# to a real accelerator (e.g. the axon tunnel) — same discipline as
# tests/conftest.py; lets bench.py run the sweep on a virtual mesh
if os.environ.get("JAX_PLATFORMS") == "cpu":
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def run_overlap(args):
    """All-gather-under-compute vs serial gather-then-compute (A/B).

    Builds the same R-round program twice: each round all-gathers a sharded
    buffer and runs a matmul chain consuming it.  ``serial`` ties every
    gather behind the previous round's compute output (depth-0 schedule);
    ``pipelined`` issues gathers ``--depth`` rounds ahead and pins each
    round's compute input on a probe of the *next* round's gather (gather
    r+1 completes before compute r; deeper prefetches stay unpinned until
    their own consumer-minus-one round) — exactly the two-sided issue
    window ``scheduled_layer_walk`` compiles for ZeRO-3.
    Overlap fraction comes from in-jit stamp spans: gather windows
    intersected with OTHER rounds' residency windows (gather_end ->
    compute_start), the span-derived overlap discipline ``Zero3CommStats``
    uses for the training schedule.
    """
    import functools

    import deepspeed_tpu.comm as dist
    from deepspeed_tpu.config import MeshConfig
    from deepspeed_tpu.utils.jax_compat import shard_map

    n = len(jax.devices())
    topo = dist.set_topology(dist.build_topology(MeshConfig(data=n)))
    mesh = topo.mesh
    dtype = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32
    itemsize = jnp.dtype(dtype).itemsize

    size_mb = float(args.sizes_mb.split(",")[0])
    numel = max(int(size_mb * 1e6 / itemsize) // n * n, n)
    R, iters = args.rounds, args.compute_iters
    m = 256
    while m * m > numel:
        m //= 2

    log = []

    def _rec(tag, _probe):
        log.append((tag, time.perf_counter()))

    def tap(x, tag):
        jax.debug.callback(functools.partial(_rec, tag), jnp.ravel(x)[:1])
        return x

    gather_sm = shard_map(
        lambda s: jax.lax.all_gather(s, "data", tiled=True),
        mesh=mesh, in_specs=(P("data"),), out_specs=P(None), check_vma=False)

    def tied(xs, t):
        # one barrier op over xs + a 1-elem probe of t: xs cannot become
        # available before t is — the issue-order tie (forward-only twin of
        # prefetch._tie_barrier; no AD needed here)
        out = jax.lax.optimization_barrier(tuple(xs) + (jnp.ravel(t)[:1],))
        return out[:-1]

    def build(depth):
        def prog(bufs, y0):
            y = y0
            pending = {}
            for r in range(R):
                for v in range(r, min(r + depth, R - 1) + 1):
                    if v not in pending:
                        (src,) = tied([bufs[v]], y)
                        src = tap(src, ("gs", v))
                        pending[v] = tap(gather_sm(src), ("ge", v))
                g = pending.pop(r)
                # completion pin one round ahead of use (the walk's deferred
                # pin): round r+1's gather must finish before compute r, while
                # deeper prefetches stay unpinned until their own r-1 — free
                # to run under intervening computes where collectives are
                # async
                if r + 1 in pending:
                    (y,) = tied([y], pending[r + 1])
                w = g[: m * m].reshape(m, m).astype(jnp.float32)
                y = tap(y, ("cs", r))
                for _ in range(iters):
                    y = jnp.tanh(y @ w)
                y = tap(y, ("ce", r))
            return y.sum()
        return jax.jit(prog)

    sharding = jax.sharding.NamedSharding(mesh, P("data"))
    bufs = [jax.device_put(jnp.asarray(np.random.randn(numel), dtype), sharding)
            for _ in range(R)]
    y0 = jnp.eye(m, dtype=jnp.float32) * 0.1

    for depth in (0, args.depth):
        fn = build(depth)
        fn(bufs, y0).block_until_ready()          # compile
        jax.effects_barrier()
        walls, fracs, g_tot, c_tot = [], [], 0.0, 0.0
        for _ in range(args.trials):
            log.clear()
            t0 = time.perf_counter()
            fn(bufs, y0).block_until_ready()
            walls.append(time.perf_counter() - t0)
            jax.effects_barrier()
            t = dict(log)
            gathers = [(t[("gs", r)], t[("ge", r)]) for r in range(R)]
            # residency = gather complete, compute not yet started: the
            # window a prefetched buffer sits parked.  Ending it at
            # compute_start (not compute_end) keeps the serial baseline
            # race-free: the next gather and the round-end tap become
            # ready at the same instant, so windows touching compute_end
            # would count executor tie-breaks as overlap.
            resident = [(t[("ge", r)], t[("cs", r)]) for r in range(R)]
            g_tot += sum(b - a for a, b in gathers)
            c_tot += sum(t[("ce", r)] - t[("cs", r)] for r in range(R))
            ov = 0.0
            for r, (a, b) in enumerate(gathers):
                merged = []
                for ra, rb in sorted(x for o, x in enumerate(resident)
                                     if o != r):
                    if merged and ra <= merged[-1][1]:
                        merged[-1] = (merged[-1][0], max(merged[-1][1], rb))
                    else:
                        merged.append((ra, rb))
                ov += sum(max(0.0, min(b, rb) - max(a, ra))
                          for ra, rb in merged)
            tot = sum(b - a for a, b in gathers)
            fracs.append(ov / tot if tot > 0 else 0.0)
        k = args.trials
        print(json.dumps({
            "op": "allgather_overlap",
            "mode": "serial" if depth == 0 else "pipelined",
            "depth": depth, "rounds": R,
            "size_mb": round(numel * itemsize / 1e6, 2), "devices": n,
            "wall_ms": round(float(np.median(walls)) * 1e3, 3),
            "gather_ms": round(g_tot / k * 1e3, 3),
            "compute_ms": round(c_tot / k * 1e3, 3),
            "overlap_frac": round(float(np.mean(fracs)), 4)}), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes-mb", default="1,4,16,64")
    ap.add_argument("--trials", type=int, default=20)
    ap.add_argument("--dtype", default="bf16", choices=["bf16", "f32"])
    ap.add_argument("--overlap", action="store_true",
                    help="run the gather-under-compute A/B leg instead of "
                         "the size sweep")
    ap.add_argument("--rounds", type=int, default=8)
    ap.add_argument("--depth", type=int, default=1,
                    help="prefetch depth for the pipelined overlap leg")
    ap.add_argument("--compute-iters", type=int, default=16)
    args = ap.parse_args()

    if args.overlap:
        run_overlap(args)
        return

    import deepspeed_tpu.comm as dist
    from deepspeed_tpu.config import MeshConfig

    n = len(jax.devices())
    topo = dist.set_topology(dist.build_topology(MeshConfig(data=n)))
    mesh = topo.mesh
    dtype = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32
    itemsize = jnp.dtype(dtype).itemsize

    from deepspeed_tpu.utils.jax_compat import shard_map

    def make(op):
        if op == "all_reduce":
            f = lambda x: jax.lax.psum(x, "data")
            spec_in = spec_out = P(None)
            corr = 2 * (n - 1) / n
        elif op == "all_gather":
            f = lambda x: jax.lax.all_gather(x, "data", tiled=True)
            spec_in, spec_out = P("data"), P(None)
            corr = (n - 1) / n
        elif op == "reduce_scatter":
            f = lambda x: jax.lax.psum_scatter(x, "data", tiled=True)
            spec_in, spec_out = P(None), P("data")
            corr = (n - 1) / n
        else:  # all_to_all
            f = lambda x: jax.lax.all_to_all(x.reshape(n, -1), "data", 0, 0,
                                             tiled=False).reshape(-1)
            spec_in = spec_out = P("data")
            corr = (n - 1) / n
        fn = jax.jit(shard_map(f, mesh=mesh, in_specs=(spec_in,),
                               out_specs=spec_out, check_vma=False))
        return fn, corr

    for size_mb in [float(x) for x in args.sizes_mb.split(",")]:
        numel = int(size_mb * 1e6 / itemsize)
        numel -= numel % (n * n)          # all_to_all divisibility
        x = jnp.asarray(np.random.randn(numel), dtype)
        for op in ("all_reduce", "all_gather", "reduce_scatter", "all_to_all"):
            fn, corr = make(op)
            out = fn(x)
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            for _ in range(args.trials):
                out = fn(x)
            jax.block_until_ready(out)
            # through remote tunnels block_until_ready may not sync; force a
            # tiny fetch as the barrier
            float(jnp.sum(out.astype(jnp.float32)[:1]))
            dt = (time.perf_counter() - t0) / args.trials
            nbytes = numel * itemsize
            algbw = nbytes / dt / 1e9
            print(json.dumps({
                "op": op, "size_mb": round(nbytes / 1e6, 2),
                "devices": n, "latency_ms": round(dt * 1e3, 3),
                "algbw_GBps": round(algbw, 2),
                "busbw_GBps": round(algbw * corr, 2)}), flush=True)


if __name__ == "__main__":
    main()
