"""Collectives micro-benchmark over the device mesh.

Parity role: the reference's communication benchmark suite
(``benchmarks/README.md`` -> DeepSpeedExamples ``benchmarks/communication``:
all_reduce/all_gather/all_to_all/pt2pt sweeps printing algbw/busbw).  Here the
same sweep drives this framework's collectives API (``deepspeed_tpu.comm``)
over whatever mesh is available — N virtual CPU devices
(``--xla_force_host_platform_device_count``), one real chip (degenerate), or a
real slice — and prints one JSON line per (op, size).

Bus bandwidth uses the standard ring-algorithm correction factors the
reference's ``utils.calc_bw`` applies: allreduce 2(n-1)/n, allgather /
reducescatter (n-1)/n, alltoall (n-1)/n.

Usage: ``python benchmarks/comm_bench.py [--sizes-mb 1,4,16,64] [--trials 20]``
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax

# honour an explicit CPU request even when a site config pins the platform
# to a real accelerator (e.g. the axon tunnel) — same discipline as
# tests/conftest.py; lets bench.py run the sweep on a virtual mesh
if os.environ.get("JAX_PLATFORMS") == "cpu":
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes-mb", default="1,4,16,64")
    ap.add_argument("--trials", type=int, default=20)
    ap.add_argument("--dtype", default="bf16", choices=["bf16", "f32"])
    args = ap.parse_args()

    import deepspeed_tpu.comm as dist
    from deepspeed_tpu.config import MeshConfig

    n = len(jax.devices())
    topo = dist.set_topology(dist.build_topology(MeshConfig(data=n)))
    mesh = topo.mesh
    dtype = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32
    itemsize = jnp.dtype(dtype).itemsize

    from jax import shard_map

    def make(op):
        if op == "all_reduce":
            f = lambda x: jax.lax.psum(x, "data")
            spec_in = spec_out = P(None)
            corr = 2 * (n - 1) / n
        elif op == "all_gather":
            f = lambda x: jax.lax.all_gather(x, "data", tiled=True)
            spec_in, spec_out = P("data"), P(None)
            corr = (n - 1) / n
        elif op == "reduce_scatter":
            f = lambda x: jax.lax.psum_scatter(x, "data", tiled=True)
            spec_in, spec_out = P(None), P("data")
            corr = (n - 1) / n
        else:  # all_to_all
            f = lambda x: jax.lax.all_to_all(x.reshape(n, -1), "data", 0, 0,
                                             tiled=False).reshape(-1)
            spec_in = spec_out = P("data")
            corr = (n - 1) / n
        fn = jax.jit(shard_map(f, mesh=mesh, in_specs=(spec_in,),
                               out_specs=spec_out, check_vma=False))
        return fn, corr

    for size_mb in [float(x) for x in args.sizes_mb.split(",")]:
        numel = int(size_mb * 1e6 / itemsize)
        numel -= numel % (n * n)          # all_to_all divisibility
        x = jnp.asarray(np.random.randn(numel), dtype)
        for op in ("all_reduce", "all_gather", "reduce_scatter", "all_to_all"):
            fn, corr = make(op)
            out = fn(x)
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            for _ in range(args.trials):
                out = fn(x)
            jax.block_until_ready(out)
            # through remote tunnels block_until_ready may not sync; force a
            # tiny fetch as the barrier
            float(jnp.sum(out.astype(jnp.float32)[:1]))
            dt = (time.perf_counter() - t0) / args.trials
            nbytes = numel * itemsize
            algbw = nbytes / dt / 1e9
            print(json.dumps({
                "op": op, "size_mb": round(nbytes / 1e6, 2),
                "devices": n, "latency_ms": round(dt * 1e3, 3),
                "algbw_GBps": round(algbw, 2),
                "busbw_GBps": round(algbw * corr, 2)}), flush=True)


if __name__ == "__main__":
    main()
